"""Multi-replica serving router (docs/SERVING.md 'Paged KV + replica tier').

One engine replica saturates at its slot/block pool; the "millions of
users" architecture is N replicas behind a device-free router.  This
module is the router half of the ``serve_replicas`` tier
(``distributed/replica_fleet.py`` owns the replica processes):

* **prefix-affinity dispatch** — requests whose prompt opens with the same
  ``serve_affinity_tokens`` tokens (the shared-system-prompt chat pattern)
  route to the SAME replica, so that replica's radix prefix cache
  (``infer/paged.py``) serves the shared span from blocks instead of
  re-prefilling it N ways.  Affinity yields to load: when the sticky
  replica carries ``serve_affinity_slack`` more in-flight requests than
  the least-loaded one, least-loaded wins (cache locality never starves
  the fleet).
* **least-loaded fallback** — cold prefixes (and affinity overflow) go to
  the replica with the fewest router-tracked in-flight requests.
* **per-replica health/breaker** — each replica carries its own
  ``serving_guard.CircuitBreaker`` (PR 3's breaker, generalized from
  per-process to per-replica): connection failures and 5xx answers count
  as failures, an OPEN replica is skipped by dispatch, a half-open one
  admits its single probe request, and a failed forward retries ONCE on a
  different healthy replica before answering the client.  All replicas
  open => 503 + Retry-After from the router without a forward.
* **chief-merged observability** — ``/health`` aggregates per-replica
  health; ``/metrics`` serves the router's own series plus every
  replica's scraped exposition RELABELED with ``replica="<i>"`` (HELP/
  TYPE lines deduped), so one scrape sees per-replica slot occupancy,
  block-pool gauges, and prefix hit rates next to the router's dispatch
  counters.

The router is deliberately DEVICE-FREE (stdlib + telemetry only — no jax
import): it runs in the parent process next to the replica fleet and its
dispatch logic is unit-testable with fake transports
(tests/router_test.py).
"""
from __future__ import annotations

import collections
import json
import re
import threading
import time
import typing
import urllib.error
import urllib.request

from .. import telemetry
from ..telemetry import events as flight
from ..telemetry import tracectx
from .serving_guard import CircuitBreaker, HTTPStatusError

#: endpoints the router forwards verbatim to a replica
FORWARD_PATHS = ("/completion", "/token_completion", "/encode", "/decode")
#: affinity-keyed (prompt-carrying) paths
COMPLETION_PATHS = ("/completion", "/token_completion")


class Replica:
    """Router-side view of one replica: address, breaker, in-flight count."""

    def __init__(self, index: int, port: int, host: str = "127.0.0.1",
                 breaker_threshold: int = 3, breaker_cooldown_s: float = 5.0,
                 clock: typing.Callable[[], float] = time.monotonic):
        self.index = int(index)
        self.host = host
        self.port = int(port)
        self.breaker = CircuitBreaker(breaker_threshold, breaker_cooldown_s,
                                      clock)
        self.inflight = 0
        self.requests = 0
        self.failures = 0
        self._lock = threading.Lock()

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def begin(self) -> None:
        with self._lock:
            self.inflight += 1
            self.requests += 1

    def done(self) -> None:
        with self._lock:
            self.inflight = max(0, self.inflight - 1)


def _http_transport(replica: Replica, path: str, body: dict,
                    timeout: float,
                    headers: typing.Optional[dict] = None
                    ) -> typing.Tuple[int, dict]:
    """Default transport: POST the body to the replica, return
    ``(status, payload)``.  Connection-level failures raise (the router
    counts them as replica failures and retries elsewhere).  ``headers``
    (the trace-id propagation) merge over the content type."""
    req = urllib.request.Request(
        replica.base_url + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read())
        except Exception:
            payload = {"error": str(e), "code": "server_error"}
        return e.code, payload


def _scrape_text(replica: Replica, path: str, timeout: float) -> str:
    with urllib.request.urlopen(replica.base_url + path,
                                timeout=timeout) as resp:
        return resp.read().decode()


def relabel_exposition(text: str, replica: int,
                       seen_meta: typing.Optional[set] = None
                       ) -> typing.List[str]:
    """Insert ``replica="<i>"`` into every sample line of a Prometheus
    text exposition; ``# HELP``/``# TYPE`` lines pass through once across
    replicas (``seen_meta`` dedupes).  Malformed lines are dropped rather
    than corrupting the merged scrape."""
    out: typing.List[str] = []
    seen_meta = seen_meta if seen_meta is not None else set()
    sample = re.compile(r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{.*\})? "
                        r"([-+0-9.eE]+|NaN|[-+]?Inf)$")
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            if line not in seen_meta:
                seen_meta.add(line)
                out.append(line)
            continue
        m = sample.match(line)
        if m is None:
            continue
        name, labels, value = m.group(1), m.group(2), m.group(3)
        inner = labels[1:-1] if labels else ""
        inner = f'replica="{replica}"' + ("," + inner if inner else "")
        out.append(f"{name}{{{inner}}} {value}")
    return out


class Router:
    """Dispatch policy + forwarding.  ``transport(replica, path, body,
    timeout)`` is injectable (tests drive the state machine with fakes)."""

    def __init__(self, replicas: typing.Sequence[Replica],
                 affinity_tokens: int = 32, affinity_slack: int = 4,
                 forward_timeout_s: float = 150.0,
                 transport: typing.Callable = _http_transport,
                 clock: typing.Callable[[], float] = time.monotonic,
                 trace_requests: bool = False):
        self.replicas = list(replicas)
        self.affinity_tokens = int(affinity_tokens)
        self.affinity_slack = int(affinity_slack)
        self.forward_timeout_s = float(forward_timeout_s)
        self.transport = transport
        self.clock = clock
        #: request tracing (docs/OBSERVABILITY.md): the router MINTS the
        #: trace id (or adopts the client's header) and propagates it to
        #: the replica, recording a router/forward span per attempt
        self.trace_requests = bool(trace_requests)
        #: prefix key -> replica index, LRU-capped
        self._affinity: "collections.OrderedDict[tuple, int]" = \
            collections.OrderedDict()
        self._affinity_cap = 4096
        self._lock = threading.Lock()
        r = telemetry.registry()
        self._m_requests = r.counter(
            "hbnlp_router_requests_total",
            "requests the router forwarded, by replica and outcome",
            ("replica", "outcome"))
        self._m_affinity = r.counter(
            "hbnlp_router_affinity_total",
            "prefix-affinity routing decisions", ("result",))
        self._m_inflight = r.gauge(
            "hbnlp_router_replica_inflight",
            "router-tracked in-flight requests per replica", ("replica",))
        self._m_breaker = r.gauge(
            "hbnlp_router_replica_breaker",
            "per-replica breaker state: 0=closed 1=half_open 2=open",
            ("replica",))

    # -- policy --------------------------------------------------------------

    def _prefix_key(self, path: str, body: dict) -> typing.Optional[tuple]:
        if self.affinity_tokens <= 0 or path not in COMPLETION_PATHS:
            return None
        if path == "/token_completion":
            toks = body.get("tokens") or []
            if not isinstance(toks, (list, tuple)) or not toks:
                return None
            return ("t",) + tuple(toks[:self.affinity_tokens])
        prompt = body.get("prompt")
        if not isinstance(prompt, str) or not prompt:
            return None
        # ~4 bytes/token for byte-level vocabularies; the key only needs to
        # be STABLE per shared system prompt, not token-exact
        return ("p", prompt[:self.affinity_tokens * 4])

    def _usable(self) -> typing.List[Replica]:
        """Replicas dispatch may target: closed or half-open breakers
        (half-open's next forward is its probe)."""
        return [r for r in self.replicas if r.breaker.tick() != "open"]

    def pick(self, path: str, body: dict) -> Replica:
        """Choose a replica, or raise 503 when every breaker is open."""
        usable = self._usable()
        if not usable:
            retry = min(r.breaker.retry_after() for r in self.replicas)
            raise HTTPStatusError(
                503, {"error": "all replicas unavailable (breakers open)",
                      "code": "unavailable"}, retry_after=max(1.0, retry))
        least = min(usable, key=lambda r: (r.inflight, r.index))
        key = self._prefix_key(path, body)
        if key is None:
            return least
        with self._lock:
            sticky = self._affinity.get(key)
            if sticky is not None:
                self._affinity.move_to_end(key)
        if sticky is not None:
            target = self.replicas[sticky]
            if (target.breaker.tick() != "open"
                    and target.inflight <= least.inflight
                    + self.affinity_slack):
                self._m_affinity.labels(result="hit").inc()
                return target
            # sticky replica open or overloaded: fall through to
            # least-loaded and re-learn the prefix there
        self._m_affinity.labels(result="miss").inc()
        with self._lock:
            self._affinity[key] = least.index
            self._affinity.move_to_end(key)
            while len(self._affinity) > self._affinity_cap:
                self._affinity.popitem(last=False)
        return least

    # -- forwarding ----------------------------------------------------------

    def forward(self, path: str, body: dict,
                headers: typing.Optional[dict] = None) -> dict:
        """Pick + transport with one cross-replica retry.  5xx answers and
        connection failures count into the source replica's breaker; 2xx
        and 4xx (client errors) count as replica health.  With tracing on,
        the client's trace header (or a freshly minted id) propagates to
        the replica and a router/forward span records each attempt."""
        trace = None
        if self.trace_requests:
            trace = tracectx.trace_id_from_headers(headers) \
                or tracectx.new_trace_id()
        first = self.pick(path, body)
        try:
            return self._forward_one(first, path, body, trace)
        except HTTPStatusError as e:
            if e.status < 500:
                raise
            retry_on = [r for r in self._usable() if r is not first]
            if not retry_on:
                raise
            second = min(retry_on, key=lambda r: (r.inflight, r.index))
            return self._forward_one(second, path, body, trace)

    def _forward_one(self, replica: Replica, path: str, body: dict,
                     trace: typing.Optional[str] = None) -> dict:
        replica.begin()
        self._m_inflight.labels(replica=str(replica.index)).set(
            replica.inflight)
        t0 = self.clock()
        outcome = "ok"
        try:
            if trace is not None:
                status, payload = self.transport(
                    replica, path, body, self.forward_timeout_s,
                    headers={tracectx.TRACE_HEADER: trace})
            else:
                status, payload = self.transport(replica, path, body,
                                                 self.forward_timeout_s)
        except HTTPStatusError:
            outcome = "error"
            raise
        except Exception as e:  # connection refused / reset / timeout
            outcome = "unreachable"
            replica.failures += 1
            replica.breaker.record_failure()
            self._m_requests.labels(replica=str(replica.index),
                                    outcome="unreachable").inc()
            raise HTTPStatusError(
                502, {"error": f"replica {replica.index} unreachable: {e}",
                      "code": "bad_gateway"})
        finally:
            replica.done()
            if trace is not None:
                # the router-dispatch hop: one span per forward ATTEMPT
                # (the cross-replica retry records its own), into the
                # router process's blackbox
                tracectx.record_span(trace, "router/forward", t0,
                                     self.clock() - t0,
                                     replica=replica.index, outcome=outcome)
            self._m_inflight.labels(replica=str(replica.index)).set(
                replica.inflight)
            self._m_breaker.labels(replica=str(replica.index)).set(
                {"closed": 0, "half_open": 1, "open": 2}.get(
                    replica.breaker.state, 0))
        if status >= 500:
            replica.failures += 1
            replica.breaker.record_failure()
            self._m_requests.labels(replica=str(replica.index),
                                    outcome="server_error").inc()
            raise HTTPStatusError(status, payload)
        # 2xx and 4xx both prove the replica is alive and answering
        replica.breaker.record_success()
        self._m_requests.labels(replica=str(replica.index),
                                outcome="ok" if status < 400
                                else "client_error").inc()
        if status >= 400:
            raise HTTPStatusError(status, payload)
        return payload

    # -- merged observability ------------------------------------------------

    def health(self, probe: typing.Optional[typing.Callable] = None) -> dict:
        """Aggregated /health: per-replica breaker + in-flight view, plus
        each replica's own /health payload when reachable.  ``status`` is
        "ok" only while at least one replica is dispatchable AND actually
        answered its probe — breakers start closed, so without the
        reachability requirement a tier whose replicas are still loading
        their model would tell a load balancer to route traffic into
        connection-refused 502s."""
        probe = probe or (lambda r: _scrape_text(r, "/health", 5.0))
        replicas = []
        reachable = 0
        for r in self.replicas:
            entry = {"replica": r.index, "port": r.port,
                     "breaker": r.breaker.tick(), "inflight": r.inflight,
                     "requests": r.requests, "failures": r.failures}
            try:
                entry["health"] = json.loads(probe(r))
                reachable += 1
            except Exception as e:
                entry["unreachable"] = str(e)
            replicas.append(entry)
        usable = bool(self._usable()) and reachable > 0
        return {"status": "ok" if usable else "unavailable",
                "tier": {"replicas": len(self.replicas),
                         "reachable": reachable,
                         "dispatchable": sum(
                             1 for r in self.replicas
                             if r.breaker.state != "open")},
                "replicas": replicas}

    def ready(self, probe: typing.Optional[typing.Callable] = None
              ) -> typing.Tuple[bool, dict]:
        """Tier readiness: at least one dispatchable replica whose OWN
        ``/ready`` answers — the startup window (ports not yet bound)
        reads not-ready, so a readiness-honoring LB holds traffic until a
        replica can actually serve."""
        probe = probe or (lambda r: _scrape_text(r, "/ready", 2.0))
        ready = 0
        for r in self._usable():
            try:
                probe(r)
                ready += 1
            except Exception:
                continue
        return ready > 0, {"ready": ready > 0, "replicas_ready": ready}

    def metrics(self, scrape: typing.Optional[typing.Callable] = None
                ) -> str:
        """Chief-merged exposition: the router's own registry + every
        reachable replica's scrape relabeled ``replica="<i>"``."""
        scrape = scrape or (lambda r: _scrape_text(r, "/metrics", 10.0))
        lines = [telemetry.prometheus_text(telemetry.snapshot()).rstrip()]
        seen_meta: set = set()
        for r in self.replicas:
            try:
                text = scrape(r)
            except Exception:
                continue  # a dead replica must not fail the fleet scrape
            lines.extend(relabel_exposition(text, r.index, seen_meta))
        return "\n".join(line for line in lines if line) + "\n"


def serve_replicated(params, workers: int = 1,
                     port: typing.Optional[int] = None,
                     stop: typing.Optional[typing.Any] = None,
                     control: typing.Optional[dict] = None):
    """Blocking replica-tier entry point (``serve_replicas`` >= 2 in
    web_api mode): spawn the replica fleet on ports ``port+1..port+N``,
    serve the router on ``port``.  ``stop`` (threading.Event-alike) tears
    the fleet down cleanly; ``control`` receives live handles for tests
    (``router``, ``fleet``)."""
    from ..distributed.replica_fleet import ReplicaFleet
    from .rest_api import DEFAULT_PORT, _run_http

    n = int(getattr(params, "serve_replicas", 0) or 0)
    if n < 2:
        raise ValueError(f"serve_replicated needs serve_replicas >= 2, "
                         f"got {n}")
    port = DEFAULT_PORT if port is None else int(port)
    telemetry.register_build_info()
    trace_on = bool(getattr(params, "trace_requests", False)) \
        and bool(getattr(params, "model_path", ""))
    if trace_on:
        # the router's own blackbox (docs/OBSERVABILITY.md 'Request
        # tracing'): router/forward spans land here, next to the replicas'
        # event files, so forensics --trace merges the whole hop chain
        flight.configure(params.model_path, "router",
                         capacity=getattr(params,
                                          "telemetry_blackbox_events", 4096))
    fleet = ReplicaFleet(params, n, base_port=port + 1)
    router = Router(
        [Replica(i, port + 1 + i,
                 breaker_threshold=int(getattr(params,
                                               "serve_breaker_threshold", 3)
                                       or 3),
                 breaker_cooldown_s=float(getattr(
                     params, "serve_breaker_cooldown_s", 5.0)))
         for i in range(n)],
        affinity_tokens=int(getattr(params, "serve_affinity_tokens", 32)),
        affinity_slack=int(getattr(params, "serve_affinity_slack", 4)),
        forward_timeout_s=float(getattr(params, "serve_request_deadline_s",
                                        120.0)) + 30.0,
        trace_requests=trace_on)
    if control is not None:
        control["router"] = router
        control["fleet"] = fleet

    def dispatch(path: str, body: dict, headers=None) -> dict:
        if path == "/health":
            payload = router.health()
            if payload["status"] != "ok":
                raise HTTPStatusError(503, payload)
            return payload
        if path == "/ready":
            ok, payload = router.ready()
            if not ok:
                raise HTTPStatusError(503, payload, retry_after=1.0)
            return payload
        if path == "/metrics":
            return {"_prometheus": router.metrics()}
        return router.forward(path, body, headers)

    paths = list(FORWARD_PATHS) + ["/health", "/ready", "/metrics"]
    # the fleet spawns NON-daemonic model-loading processes: everything
    # from start() on runs under the finally that stops them, or a failure
    # in the setup window would leave the interpreter joining N orphaned
    # replicas forever at exit
    try:
        fleet.start()
        server = threading.Thread(
            target=_run_http,
            args=(port, paths, dispatch, workers),
            kwargs={"max_body_bytes": int(getattr(params,
                                                  "serve_max_body_bytes",
                                                  0) or 0)},
            daemon=True)
        server.start()
        print(f"replica tier on :{port} — router + {n} replicas on "
              f":{port + 1}..:{port + n}")
        while stop is None or not stop.is_set():
            fleet.poll()
            if trace_on:
                flight.maybe_flush(2.0)
            if stop is None:
                time.sleep(1.0)
            else:
                stop.wait(1.0)
    finally:
        if trace_on:
            flight.flush(reason="router-exit")
        fleet.stop()
