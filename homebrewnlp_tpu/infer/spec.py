"""Draft-model plumbing for speculative decoding (docs/SERVING.md).

The engine's draft-and-verify loop (infer/engine.py ``SpecEngineExecutor``)
needs a SECOND model — the quarter-width draft — restored alongside the
serving target.  Per the one-graph-many-layouts thesis the draft is the
SAME model definition at a smaller shape (the committed
``configs/1b_long_context_draft_247m.json`` artifact), not a forked code
path: this module loads its config, restores its checkpoint through the
same corruption-tolerant ``restore_latest_valid`` walk the target uses
(train/checkpoint.py), and builds the batch-width views the slot engine
decodes through.

A draft triple is ``(params, model, variables)``; callers that already hold
one (the serving bench distills its own) attach it as ``interface.draft``
and skip the loader entirely.
"""
from __future__ import annotations

import json
import os
import typing

from ..config import ModelParameter

#: the draft triple: (ModelParameter, Model, variables)
DraftTriple = typing.Tuple[typing.Any, typing.Any, typing.Dict[str, typing.Any]]


def draft_config_path(path: str) -> str:
    """Resolve ``spec_draft_model_path`` to a config JSON: the path itself
    when it names a JSON file, else ``<path>/config.json``."""
    if path.endswith(".json"):
        return path
    return os.path.join(path, "config.json")


def load_draft(params: ModelParameter) -> DraftTriple:
    """Build + restore the draft model named by ``spec_draft_model_path``.

    The draft's variables restore from ITS config's ``model_path`` through
    ``restore_latest_valid(strict=True)`` — a corrupt draft run refuses to
    serve random drafts silently, exactly like the target's loader
    (run/modes.py ``_load_model``).  A draft with NO checkpoints loads at
    random init with a loud note: acceptance will be ~zero and the engine's
    ``spec_min_accept_rate`` self-disable is expected to fire — useful for
    smoke tests, never for production.
    """
    import numpy as np

    from ..model import Model
    from ..train import checkpoint as ckpt

    path = str(getattr(params, "spec_draft_model_path", "") or "")
    if not path:
        raise ValueError("spec_decode needs spec_draft_model_path (a config "
                         "JSON or a checkpoint dir with config.json)")
    cfg_path = draft_config_path(path)
    with open(cfg_path) as f:
        cfg = json.load(f)
    # serving-shape knobs follow the TARGET: the draft rides the same
    # token_x (same sequence geometry) and the same slot pool width
    cfg.update(sequence_length=params.sequence_length,
               token_patch_size=params.token_patch_size,
               train_batch_size=1)
    dparams = ModelParameter(cfg)
    dparams.train = False
    check_draft_compatible(params, dparams)
    dmodel = Model(dparams)
    seq = dparams.sequence_dim.size
    zeros = np.zeros((1, seq, dparams.token_patch_dim.size), np.int32)
    variables = dmodel.init({"token_x": zeros, "token_y": zeros})
    restored = ckpt.restore_latest_valid(dparams.model_path, strict=True)
    if restored:
        loaded, _, step, _ = restored
        variables = {k: np.asarray(loaded[k]).astype(variables[k].dtype)
                     if k in loaded else v for k, v in variables.items()}
        print(f"loaded draft checkpoint at step {step} ({dparams.model_path})")
    else:
        print(f"WARNING: draft {dparams.model_path} has no checkpoint — "
              "drafting from RANDOM init (acceptance ~0; expect the "
              "spec_min_accept_rate self-disable to fire)")
    import jax.numpy as jnp
    return dparams, dmodel, {k: jnp.asarray(v) for k, v in variables.items()}


def check_draft_compatible(params: ModelParameter,
                           dparams: ModelParameter) -> None:
    """The draft decodes the TARGET's token stream in place: vocabulary and
    sequence geometry must match exactly, and both must be streaming text
    models.  Raises ValueError naming the mismatch."""
    for knob in ("vocab_size", "sequence_length", "token_patch_size"):
        a, b = getattr(params, knob), getattr(dparams, knob)
        if a != b:
            raise ValueError(f"draft/target {knob} mismatch: target {a}, "
                             f"draft {b} — the draft rides the target's "
                             "token stream and must share its geometry")
    if dparams.use_video or not dparams.use_language:
        raise ValueError("the draft must be a text (gpt-mode) model")


def draft_for_width(draft: DraftTriple, width: int) -> DraftTriple:
    """A batch-``width`` view over the SAME draft variables (the shared
    ``interface.model_width_view`` helper — plan/param-dims sharing lives
    in exactly one place)."""
    from .interface import model_width_view

    dparams, dmodel, dvariables = draft
    if dparams.train_batch_size == width:
        return draft
    p, m = model_width_view(dparams, dmodel, width)
    return p, m, dvariables
