"""Host-side scheduling for the continuous-batching engine.

Deliberately device-free (stdlib + numpy only — no jax import anywhere), so
the scheduler state machine is testable with a fake clock and a fake
executor (tests/continuous_batching_test.py, marker ``contbatch``):

* :class:`EngineRequest` — one parsed completion riding the engine, with its
  admission timestamp, deadline, and decode extent.
* :class:`SlotScheduler` — FIFO pending queue x fixed slot set: admit-order
  fairness, slot exhaustion queues (never errors), deadline expiry for both
  queued and resident requests, recycling of finished slots.
* :class:`EngineController` — one serving round: expire -> (breaker) ->
  admit -> dispatch -> extract.  The executor is injected
  (``infer.engine.EngineExecutor`` in production) and must expose
  ``slots``/``seq``, ``admit(slot, req)``, ``release(slot)``,
  ``dispatch(steps) -> positions``, ``tokens(slot)``, ``reset()``.

Exactly-one-answer invariant: every submitted request leaves the scheduler
through exactly one of ``answer(req, outcome)``'s outcomes — ``("ok",
tokens)``, ``("timeout", where)``, ``("error", exc)``, or ``("unavailable",
retry_after)`` — mirroring PR 3's batch-path guarantee per slot.

PR 3 mechanics carry over per slot: a deadline-expired RESIDENT is evicted
at the next chunk boundary (answered 504 by the caller); a failed dispatch
answers every resident as a decode failure and counts ONE event into the
breaker; an open breaker sheds the pending queue without a device call and
half-open admits a single probe request.
"""
from __future__ import annotations

import collections
import dataclasses
import time
import typing


@dataclasses.dataclass
class EngineRequest:
    """One parsed completion request riding the engine."""
    rid: str
    path: str
    toks: typing.Any                      # prompt tokens (1-D int array)
    temperature: float = 0.0
    response_len: typing.Optional[int] = None
    top_k: typing.Optional[int] = None
    top_p: typing.Optional[float] = None
    rep_penalty: typing.Optional[float] = None
    deadline: typing.Optional[float] = None    # monotonic; None = none
    enqueue_ts: typing.Optional[float] = None  # HTTP-child admission stamp
    submitted_ts: float = 0.0                  # set by SlotScheduler.submit
    #: cross-process trace id (docs/OBSERVABILITY.md 'Request tracing'):
    #: minted at the router / HTTP edge, riding the request tuple; None
    #: when tracing is off — the scheduler never reads it, it only carries
    trace: typing.Optional[str] = None

    def prompt_len(self, seq: int) -> int:
        """Prompt tokens the decode keeps (clipped to capacity, matching
        ``InterfaceWrapper.complete_tokens``)."""
        return min(len(self.toks), seq - 1)

    def end_pos(self, seq: int) -> int:
        """The slot's decode extent: prompt + response cap, clipped."""
        n = self.prompt_len(seq)
        if self.response_len is None:
            return seq
        return min(seq, n + int(self.response_len))


def spec_depth(req: EngineRequest, defaults: typing.Tuple[int, float, float],
               k: int) -> int:
    """Per-slot draft depth for the speculative engine: ``k`` for requests
    the accept rule can serve BIT-identically — greedy (temperature 0) with
    every logits filter at its disabled default — and 0 for everything
    else.  A depth-0 slot rides the same verify step but advances exactly
    one sampled token per round (the plain-step semantics), so mixed
    workloads co-reside in one chunk program instead of forking the engine.

    ``defaults`` are the config fallbacks ``(top_k, top_p, rep_penalty)``
    that apply when the request leaves a knob unset (the executor's
    ``_defaults``); the repetition penalty matters because the verify
    scores all k+1 positions with the ``seen`` counts as of the ROUND
    START — exact for one token, stale for drafted positions beyond it."""
    if float(req.temperature) != 0.0:
        return 0
    tk, tp, rp = defaults
    top_k = tk if req.top_k is None else int(req.top_k)
    top_p = tp if req.top_p is None else float(req.top_p)
    rep = rp if req.rep_penalty is None else float(req.rep_penalty)
    if top_k > 0 or top_p < 1.0 or rep != 1.0:
        return 0
    return int(k)


class SlotScheduler:
    """FIFO pending queue over a fixed slot set."""

    def __init__(self, slots: int,
                 clock: typing.Callable[[], float] = time.monotonic):
        self.slots = int(slots)
        self.clock = clock
        self.pending: typing.Deque[EngineRequest] = collections.deque()
        #: slot -> (request, admitted_ts)
        self.resident: typing.Dict[int, typing.Tuple[EngineRequest, float]] \
            = {}
        self._free = list(range(self.slots))

    # -- queue side ----------------------------------------------------------

    def submit(self, req: EngineRequest) -> None:
        """Queue a request.  Slot exhaustion only ever queues — the 429
        admission budget lives at the HTTP edge (serving_guard), not here."""
        req.submitted_ts = self.clock()
        self.pending.append(req)

    def drain_pending(self) -> typing.List[EngineRequest]:
        """Remove and return every queued request (breaker-open shedding)."""
        out = list(self.pending)
        self.pending.clear()
        return out

    # -- deadlines -----------------------------------------------------------

    def expire(self, now: typing.Optional[float] = None
               ) -> typing.Tuple[typing.List[EngineRequest],
                                 typing.List[typing.Tuple[int, EngineRequest]]]:
        """Remove deadline-expired requests: returns ``(queued, resident)``
        where resident entries are ``(slot, request)`` and their slots are
        already recycled — the caller answers each 504 exactly once."""
        now = self.clock() if now is None else now
        queued = [r for r in self.pending
                  if r.deadline is not None and now >= r.deadline]
        if queued:
            gone = set(id(r) for r in queued)
            self.pending = collections.deque(
                r for r in self.pending if id(r) not in gone)
        evicted = []
        for slot, (req, _) in sorted(self.resident.items()):
            if req.deadline is not None and now >= req.deadline:
                evicted.append((slot, req))
        for slot, _ in evicted:
            del self.resident[slot]
            self._free.append(slot)
        return queued, evicted

    # -- slots ---------------------------------------------------------------

    def admit(self, now: typing.Optional[float] = None,
              limit: typing.Optional[int] = None,
              fits: typing.Optional[typing.Callable[[EngineRequest], bool]]
              = None
              ) -> typing.List[typing.Tuple[int, EngineRequest, float]]:
        """Place queued requests into free slots, strictly FIFO.  Returns
        ``(slot, request, queue_wait_seconds)`` per admission.

        ``fits(req)`` (optional — the paged executor's ``can_admit``) gates
        each admission on executor capacity beyond slot count (KV block
        reservations): a False answer stops admission AT THE HEAD — the
        request stays queued (exhaustion queues, never errors) and nothing
        behind it skips ahead, preserving FIFO fairness."""
        now = self.clock() if now is None else now
        out = []
        budget = len(self._free) if limit is None else min(limit,
                                                           len(self._free))
        while self.pending and budget > 0:
            if fits is not None and not fits(self.pending[0]):
                break
            req = self.pending.popleft()
            slot = self._free.pop(0)
            self.resident[slot] = (req, now)
            out.append((slot, req, max(0.0, now - req.submitted_ts)))
            budget -= 1
        return out

    def finish(self, slot: int, now: typing.Optional[float] = None
               ) -> typing.Tuple[EngineRequest, float]:
        """Recycle a finished slot; returns ``(request, residency_s)``."""
        now = self.clock() if now is None else now
        req, admitted = self.resident.pop(slot)
        self._free.append(slot)
        return req, max(0.0, now - admitted)

    def clear_residents(self) -> typing.List[typing.Tuple[int, EngineRequest]]:
        """Remove every resident (failed-dispatch recovery); slots free."""
        out = sorted((slot, req) for slot, (req, _) in self.resident.items())
        self.resident.clear()
        self._free = list(range(self.slots))
        return out

    @property
    def free_slots(self) -> int:
        return len(self._free)

    def depth(self) -> int:
        """Requests holding admission budget: queued + engine-resident."""
        return len(self.pending) + len(self.resident)


class EngineController:
    """One serving round of the continuous engine, orchestration only.

    ``answer(req, outcome)`` is the caller's responder; ``hooks(event,
    **kw)`` (optional) receives ``admitted`` (queue_age=), ``evicted``,
    ``recycled`` (residency=), ``chunk`` (dt=, steps=, cache_bytes=),
    ``first_token`` (reqs=[...]), and — paged executors only — ``pool``
    (the ``pool_stats()`` occupancy/sharing dict) — ``rest_api`` turns
    these into the /metrics slot + block series and the TTFT/ITL
    histograms.
    """

    def __init__(self, executor, scheduler: SlotScheduler, guard=None,
                 clock: typing.Callable[[], float] = time.monotonic,
                 decode_chunk: int = 64, prefill_chunk: int = 128,
                 answer: typing.Optional[typing.Callable] = None,
                 hooks: typing.Optional[typing.Callable] = None):
        self.executor = executor
        self.sched = scheduler
        self.guard = guard
        self.clock = clock
        self.decode_chunk = max(1, int(decode_chunk))
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.answer = answer or (lambda req, outcome: None)
        self.hooks = hooks or (lambda event, **kw: None)
        #: per-slot first-token-reported flags (TTFT closes exactly once)
        self._first_done: typing.Dict[int, bool] = {}
        #: what the LAST planned dispatch was doing ("prefill" while any
        #: resident is still walking its prompt, else "decode") — rides the
        #: chunk hook so the request tracer can name chunk-occupancy spans
        self.last_phase = "decode"

    # -- helpers -------------------------------------------------------------

    def _plan_steps(self) -> int:
        """Per-dispatch iteration budget: ``serve_prefill_chunk_tokens``
        bounds how far prompt walking runs between scheduling boundaries
        while any admitted request is still consuming its prompt;
        ``decode_chunk_tokens`` is the steady-state granularity.  The
        compiled loop exits early once every live slot reaches its end, so
        over-budgeting costs nothing."""
        walk = 0
        for slot, (req, _) in self.sched.resident.items():
            remaining = (max(1, req.prompt_len(self.executor.seq)) - 1
                         - int(self.executor.q[slot]))
            walk = max(walk, remaining)
        if walk > 0:
            self.last_phase = "prefill"
            return max(1, min(self.prefill_chunk, walk))
        self.last_phase = "decode"
        return self.decode_chunk

    def _fail_residents(self, exc: Exception) -> None:
        """Failed dispatch: every resident is answered as a decode failure
        (their in-pool state is gone with the donated carry), ONE event
        counts into the breaker, and the pool re-initialises next round."""
        if self.guard is not None:
            self.guard.record_decode_failure()
        for slot, req in self.sched.clear_residents():
            self._first_done.pop(slot, None)
            self.answer(req, ("error", exc))
        self.executor.reset()

    # -- one round -----------------------------------------------------------

    def round(self, new_requests: typing.Sequence[EngineRequest] = ()
              ) -> bool:
        """Admit/evict + at most one chunk dispatch.  Returns True when a
        dispatch ran (the caller's idle detection)."""
        now = self.clock()
        for req in new_requests:
            self.sched.submit(req)
        # deadlines first: an expired resident is evicted at this chunk
        # boundary — answered 504 exactly once, slot recycled immediately
        queued, evicted = self.sched.expire(now)
        for req in queued:
            self.answer(req, ("timeout", "queue"))
        for slot, req in evicted:
            self.executor.release(slot)
            self._first_done.pop(slot, None)
            self.hooks("evicted", req=req)
            self.answer(req, ("timeout", "slot"))
        breaker = self.guard.breaker.tick() if self.guard is not None \
            else "closed"
        if breaker == "open":
            ra = self.guard.breaker.retry_after()
            for req in self.sched.drain_pending():
                self.answer(req, ("unavailable", ra))
            return False
        # half-open: exactly ONE request probes the device (the PR 3
        # single-probe rule, per slot) — the rest stay queued, not shed
        limit = None
        if breaker == "half_open":
            limit = max(0, 1 - len(self.sched.resident))
        fits = getattr(self.executor, "can_admit", None)
        if fits is None:
            admitted = self.sched.admit(now, limit=limit)
            for slot, req, waited in admitted:
                self.executor.admit(slot, req)
        else:
            # one admission at a time: each executor.admit RESERVES its
            # block need, and the next head-of-queue fits check must see
            # that reservation — a batched check would over-admit past
            # the pool
            admitted = []
            while limit is None or len(admitted) < limit:
                one = self.sched.admit(now, limit=1, fits=fits)
                if not one:
                    break
                self.executor.admit(one[0][0], one[0][1])
                admitted += one
            if self.sched.pending and self.sched.free_slots > 0 \
                    and (limit is None or len(admitted) < limit):
                # admission stopped at the FIFO head with slots free: the
                # head is waiting on KV blocks, not a slot — surface it so
                # the request tracer can close a block-wait span at its
                # eventual admission (docs/OBSERVABILITY.md)
                self.hooks("kv_block_wait", req=self.sched.pending[0])
        for slot, req, waited in admitted:
            self._first_done[slot] = False
            self.hooks("admitted", queue_age=waited, req=req)
        if not self.sched.resident:
            return False
        steps = self._plan_steps()
        q_before = self.executor.q.copy()
        t0 = self.clock()
        try:
            q_after = self.executor.dispatch(steps)
        except Exception as exc:  # noqa: BLE001 — any device fault
            self._fail_residents(exc)
            return True
        dt = self.clock() - t0
        if self.guard is not None:
            self.guard.record_decode_success()
        advanced = int(max(0, (q_after - q_before).max()))
        seq = self.executor.seq
        # acceptance-aware dispatch (speculative engine): the executor
        # records per-verify accept/draft counts and a one-shot self-disable
        # — forward them as hook events so the serving layer can export the
        # acceptance economics (hbnlp_spec_* series) without the scheduler
        # knowing the engine flavor
        take = getattr(self.executor, "take_spec_events", None)
        if take is not None:
            for ev in take():
                self.hooks("spec_" + ev.pop("kind"), **ev)
        # tokens generated this chunk: per row, write positions q+1..q' that
        # lie at/past the prompt boundary (prompt-walking steps don't count)
        generated = 0
        for slot, (req, _) in self.sched.resident.items():
            thr = max(1, req.prompt_len(seq))
            generated += max(0, int(q_after[slot])
                             - max(int(q_before[slot]), thr - 1))
        # resident is the scheduler's LIVE dict (slot -> (req, admitted)),
        # not a copy: only the request tracer consumes it, and building a
        # per-chunk list would tax every untraced deployment's hot loop
        # program: the ENGINE_PROGRAMS registry name of the composition
        # that served this chunk (compositions can change live — the spec
        # self-disable recomposes the Engine without the draft pool)
        engine = getattr(self.executor, "engine", None)
        self.hooks("chunk", dt=dt, steps=advanced, generated=generated,
                   cache_bytes=getattr(self.executor, "cache_bytes", 0),
                   phase=self.last_phase, resident=self.sched.resident,
                   program=getattr(engine, "name", None))
        # paged executor: per-chunk block-pool occupancy + sharing stats
        # flow through the same hook seam (rest_api exports the hbnlp_kv_*
        # gauges from them; the scheduler stays engine-flavor-agnostic)
        pool_stats = getattr(self.executor, "pool_stats", None)
        if pool_stats is not None:
            self.hooks("pool", **pool_stats())
        first, finished = [], []
        for slot, (req, _) in sorted(self.sched.resident.items()):
            threshold = max(1, req.prompt_len(seq))
            if not self._first_done.get(slot) and q_after[slot] >= threshold:
                self._first_done[slot] = True
                first.append(req)
            if q_after[slot] >= req.end_pos(seq) - 1:
                finished.append(slot)
        if first:
            self.hooks("first_token", reqs=first)
        for slot in finished:
            tokens = self.executor.tokens(slot)
            req, residency = self.sched.finish(slot, self.clock())
            self.executor.release(slot)
            # a zero-generation request (end at/below its prompt) may never
            # cross the first-token threshold: close its TTFT at completion
            # (the stepped loop's flush_first_tokens rule)
            if not self._first_done.pop(slot, True):
                self.hooks("first_token", reqs=[req])
            self.hooks("recycled", residency=residency, req=req)
            self.answer(req, ("ok", tokens))
        return True
