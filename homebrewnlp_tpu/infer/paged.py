"""Paged KV cache with radix prefix sharing (docs/SERVING.md 'Paged KV').

The slot engine (``infer/engine.py``) reserves ``slots x worst-case-length``
KV rows on device — every slot owns a full-sequence stripe of every cache
leaf whether it holds a 4-token ping or a 4k-token document, and every
admission re-prefills its whole prompt even when co-served requests share a
system prompt.  This module replaces the fixed stripes with a BLOCK POOL:

* **block pool** — each cache leaf with a full sequence axis is re-laid-out
  as ``[num_blocks, block_tokens, ...]`` (slot axis -> physical blocks, seq
  axis -> block-local rows).  A host-side free list + refcounts
  (:class:`BlockPool`) hand blocks to requests as their decode extent
  grows, so device KV memory tracks LIVE tokens; the slot recycler's
  per-leaf row-zeroing becomes block alloc/free.  Leaves without a full
  sequence axis (cumsum totals, conv windows — sequence-RECURRENT state)
  stay resident per slot exactly as in the slot engine.
* **per-slot block tables** — the donated chunk step takes int32
  ``[slots, seq_blocks]`` READ and WRITE tables.  At chunk entry every
  paged leaf is gathered into per-slot full-length views
  (``model/decode.py gather_blocks``; unmapped entries read ZEROS — the
  paged analogue of the slot engine's cleared rows), the UNCHANGED engine
  loop (``engine._engine_loop`` — one definition, so paged-vs-plain greedy
  bit-parity holds by construction) runs its iterations on the views, and
  the views scatter back through the write table (``scatter_blocks``;
  read-only shared blocks DROP).  The pool leaves ride the donated carry
  and alias input->output (HLO-audited as ``paged_chunk_step``).
* **radix prefix sharing** — a radix tree (:class:`RadixIndex`) over
  prompt-token block keys.  An admitted prompt that matches a cached path
  REFERENCES the shared blocks (read table -> shared id, write table ->
  unmapped) and starts decoding at the divergence point: prefill is
  skipped over the shared span, so a prefix-hit TTFT collapses to one
  chunk.  A partial match inside a block is COPY-ON-WRITE: the read table
  points at the shared parent block, the write table at a fresh private
  block — the chunk's gather/scatter round-trip IS the copy, and the
  parent block is never written (tests pin it bit-unchanged).  Finished
  requests return their private blocks; fully-walked prompt blocks are
  promoted into the tree (refcount-0 -> LRU-evictable cache) for future
  hits.  Sharing needs every position-indexed leaf to be paged, so models
  carrying sequence-recurrent caches page WITHOUT sharing (their recurrent
  state cannot be restored at a nonzero admission position).

Correctness notes.  Shared rows hold exactly the KV a cold walk would
write (decode is deterministic in tokens+position, including the int8
per-row quantization), stale rows in freshly-allocated blocks sit strictly
ABOVE every live position and are causally masked until overwritten (the
slot engine's own self-heal argument), and the admit splice zeroes the
admitted slot's view rows at/past the shared length — with sharing off
that is the slot engine's uniform clear, bit for bit.  Greedy parity with
the plain engine, including admission into reclaimed (dirty) blocks and
prefix-hit admissions, is pinned token-for-token by tests/paged_kv_test.py.

``BlockPool`` and ``RadixIndex`` are deliberately device-free (stdlib +
numpy, no jax import) so the block-lifecycle state machine tests run
without device work — the ``infer/scheduler.py`` idiom.
"""
from __future__ import annotations

import collections
import typing

import numpy as np

from .engine import Engine, EngineExecutor, SpecEngineExecutor


# --------------------------------------------------------------- block pool

class BlockPool:
    """Physical-block accounting: free list, per-block slot refcounts, and
    admission reservations.  Blocks are abstract ids ``0..num_blocks-1``;
    the device-side pools are indexed by them through the block tables.

    States: *free* (on the free list), *live* (refcount >= 1, referenced
    by at least one resident slot's table), *cached* (refcount 0 but still
    holding radix-tree content — reclaimable on demand).  Double-frees and
    deref-below-zero raise — a refcount bug silently corrupts co-served
    requests, so the negative control is a hard error."""

    def __init__(self, num_blocks: int):
        self.num_blocks = int(num_blocks)
        self._free: typing.Deque[int] = collections.deque(
            range(self.num_blocks))
        self._on_free = [True] * self.num_blocks
        self._ref = [0] * self.num_blocks
        self.reserved_total = 0

    # -- lifecycle -----------------------------------------------------------

    def alloc(self) -> int:
        """Take a block off the free list with refcount 1; raises
        ``IndexError`` when empty (callers evict or queue — never 500)."""
        b = self._free.popleft()
        self._on_free[b] = False
        self._ref[b] = 1
        return b

    def addref(self, block: int) -> None:
        if self._on_free[block]:
            raise ValueError(f"block {block} is free — addref on a freed "
                             "block is a lifecycle bug")
        self._ref[block] += 1

    def deref(self, block: int) -> int:
        """Drop one reference; returns the remaining count.  Deref of a
        free or zero-ref block raises (the double-free negative control)."""
        if self._on_free[block] or self._ref[block] <= 0:
            raise ValueError(f"double-free of block {block} "
                             f"(ref={self._ref[block]}, "
                             f"free={self._on_free[block]})")
        self._ref[block] -= 1
        return self._ref[block]

    def reclaim(self, block: int) -> None:
        """Return a refcount-0 block to the free list."""
        if self._on_free[block]:
            raise ValueError(f"double-free of block {block} (already on "
                             "the free list)")
        if self._ref[block] != 0:
            raise ValueError(f"reclaim of live block {block} "
                             f"(ref={self._ref[block]})")
        self._on_free[block] = True
        self._free.append(block)

    # -- accounting ----------------------------------------------------------

    def refcount(self, block: int) -> int:
        return self._ref[block]

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def live_count(self) -> int:
        return sum(1 for r in self._ref if r > 0)

    def reserve(self, n: int) -> None:
        self.reserved_total += int(n)

    def unreserve(self, n: int) -> None:
        self.reserved_total = max(0, self.reserved_total - int(n))

    def available(self, evictable: int = 0) -> int:
        """Blocks an admission could still claim: free + cache-evictable,
        minus capacity already promised to admitted-but-growing requests."""
        return self.free_count + int(evictable) - self.reserved_total


# --------------------------------------------------------------- radix tree

class _Node:
    __slots__ = ("key", "block", "children", "parent", "touch")

    def __init__(self, key, block, parent):
        self.key = key          # tuple of block_tokens prompt tokens
        self.block = block      # physical block id (None for the root)
        self.children: typing.Dict[tuple, "_Node"] = {}
        self.parent = parent
        self.touch = 0


class RadixIndex:
    """Radix tree over prompt-token BLOCK keys.

    A path from the root spells a prompt prefix in whole blocks; each node
    holds the physical block whose KV rows cover its span.  ``lookup``
    returns the longest cached path for a prompt plus an optional PARTIAL
    match (longest common token prefix against one child's key — the
    copy-on-write divergence point).  Nodes are LRU-stamped on every
    lookup/insert; ``evict_lru`` removes the least-recently-touched
    refcount-0 LEAF and reclaims its block (a referenced child always
    implies a referenced parent — paths are reference-prefixes — so a
    refcount-0 block guarantees a refcount-0 leaf exists)."""

    def __init__(self, block_tokens: int):
        self.block_tokens = int(block_tokens)
        self.root = _Node(None, None, None)
        self._by_block: typing.Dict[int, _Node] = {}
        self._clock = 0

    def _tick(self, node: _Node) -> None:
        self._clock += 1
        node.touch = self._clock

    def holds(self, block: int) -> bool:
        return block in self._by_block

    def __len__(self) -> int:
        return len(self._by_block)

    def evictable_count(self, pool: BlockPool) -> int:
        return sum(1 for b in self._by_block if pool.refcount(b) == 0)

    def lookup(self, tokens: typing.Sequence[int]
               ) -> typing.Tuple[typing.List[_Node],
                                 typing.Optional[_Node], int]:
        """``(full_path_nodes, partial_node, partial_depth)`` for the
        longest cached prefix of ``tokens``; touches matched nodes."""
        toks = [int(t) for t in tokens]
        b = self.block_tokens
        node, full = self.root, []
        i = 0
        while i + b <= len(toks):
            child = node.children.get(tuple(toks[i:i + b]))
            if child is None:
                break
            self._tick(child)
            full.append(child)
            node = child
            i += b
        rest = toks[i:i + b]
        best, depth = None, 0
        for child in node.children.values():
            d = 0
            for a, c in zip(rest, child.key):
                if a != c:
                    break
                d += 1
            if d > depth:
                best, depth = child, d
        if best is not None:
            self._tick(best)
        return full, best, depth

    def insert(self, parent: typing.Optional[_Node], key: tuple,
               block: int) -> _Node:
        """Add ``key -> block`` under ``parent`` (None = root).  If an
        identical child already exists the EXISTING node wins (its block
        is the canonical copy) and the caller's block stays private."""
        parent = parent or self.root
        child = parent.children.get(tuple(key))
        if child is not None:
            self._tick(child)
            return child
        child = _Node(tuple(key), int(block), parent)
        parent.children[child.key] = child
        self._by_block[child.block] = child
        self._tick(child)
        return child

    def evict_lru(self, pool: BlockPool) -> bool:
        """Remove the least-recently-touched refcount-0 leaf and reclaim
        its block; False when nothing is evictable."""
        best = None
        for block, node in self._by_block.items():
            if node.children or pool.refcount(block) != 0:
                continue
            if best is None or node.touch < best.touch:
                best = node
        if best is None:
            return False
        del best.parent.children[best.key]
        del self._by_block[best.block]
        pool.reclaim(best.block)
        return True

    def clear(self) -> None:
        self.root = _Node(None, None, None)
        self._by_block.clear()


# ------------------------------------------------------- leaf classification

def classify_cache_leaves(shapes: typing.Mapping[str, typing.Any],
                          seq: int) -> typing.Dict[str, tuple]:
    """``{leaf_name: (batch_axis, seq_axis_or_None)}`` over a
    ``decode_cache_shapes`` pytree.  The batch (slot) axis follows the
    engine's convention (axis 1 for depth-stacked leaves, else 0); the
    sequence axis is the first full-``seq``-sized axis after it — the
    position ``spread`` writes rows at.  Leaves without one (running sums,
    conv windows) are sequence-recurrent: resident per slot, unpaged, and
    incompatible with prefix sharing."""
    from ..model import blocks as blocks_mod

    info = {}
    for name, s in shapes.items():
        baxis = 1 if name.startswith(blocks_mod.STACKED_CACHE_PREFIX) else 0
        sax = None
        for ax in range(baxis + 1, len(s.shape)):
            if s.shape[ax] == seq:
                sax = ax
                break
        info[name] = (baxis, sax)
    return info


# -------------------------------------------------------- paged chunk step

def _paged_jit(model, mesh, kind: str, block_tokens: int, num_blocks: int):
    """Compat shim: the retired ``paged_init``/``paged_admit``/
    ``paged_plain`` kind names onto the Engine's single builder
    (``engine._chunk_jit`` with the ``paged`` component — the gather /
    shared-loop / scatter body now lives there, once, for both the paged
    and the spec-on-paged compositions)."""
    from .engine import _chunk_jit

    return _chunk_jit(model, mesh, kind.split("_", 1)[1],
                      paged=(int(block_tokens), int(num_blocks)))


# ------------------------------------------------------------- the executor

class PagedEngineExecutor(EngineExecutor):
    """The slot engine with its KV stripes replaced by the block pool.

    Same executor surface the controller drives (``admit``/``release``/
    ``dispatch``/``tokens``/``reset``) plus ``can_admit`` (the scheduler's
    fits-gate: free-list exhaustion QUEUES instead of erroring) and
    ``pool_stats`` (the /metrics block gauges).  Construction raises
    ``NotImplementedError`` for geometries paging cannot serve (sequence
    not divisible by the block size) — ``kv_paging="auto"`` falls back to
    the plain engine on that signal, ``"on"`` surfaces it."""

    def __init__(self, interface, slots: int,
                 seed: typing.Optional[int] = None,
                 block_tokens: typing.Optional[int] = None,
                 pool_blocks: typing.Optional[int] = None):
        from .sampler import decode_cache_shapes

        super().__init__(interface, slots, seed=seed)
        p = interface.params
        self.block_tokens = int(block_tokens
                                if block_tokens is not None
                                else getattr(p, "kv_block_tokens", 16))
        if self.block_tokens < 1:
            raise ValueError("kv_block_tokens must be >= 1")
        if self.seq % self.block_tokens:
            raise NotImplementedError(
                f"kv_paging needs the sequence length in patches "
                f"({self.seq}) divisible by kv_block_tokens "
                f"({self.block_tokens})")
        self.seq_blocks = self.seq // self.block_tokens
        probe = np.zeros((self.slots, self.seq, self.tps), np.int32)
        shapes = decode_cache_shapes(self.model_w, self.variables, probe)
        self.leaf_info = classify_cache_leaves(shapes, self.seq)
        nb = int(pool_blocks if pool_blocks is not None
                 else getattr(p, "kv_pool_blocks", 0) or 0)
        self.num_blocks = nb or self.slots * self.seq_blocks
        if self.num_blocks < self.seq_blocks:
            raise ValueError(
                f"kv_pool_blocks={self.num_blocks} cannot hold even one "
                f"full-length request ({self.seq_blocks} blocks)")
        # prefix sharing needs EVERY position-indexed leaf paged: a
        # sequence-recurrent resident leaf (cumsum/conv window) cannot be
        # restored at a nonzero admission position, so such models page
        # without sharing (admissions always walk their full prompt)
        self.sharing = all(sax is not None
                           for _, sax in self.leaf_info.values())
        self.tree = RadixIndex(self.block_tokens) if self.sharing else None
        self.pool = BlockPool(self.num_blocks)
        self.SENTINEL = self.num_blocks
        self.rtable = np.full((self.slots, self.seq_blocks), self.SENTINEL,
                              np.int32)
        self.wtable = np.full((self.slots, self.seq_blocks), self.SENTINEL,
                              np.int32)
        self._keep_len = np.zeros(self.slots, np.int32)
        self._owned: typing.List[set] = [set() for _ in range(self.slots)]
        self._shared: typing.List[list] = [[] for _ in range(self.slots)]
        self._reserved = [0] * self.slots
        #: per-slot promotion cursor: (tree node to insert under, next
        #: block index to consider)
        self._promo: typing.List[typing.Optional[tuple]] = \
            [None] * self.slots
        self._prompt_toks: typing.List[typing.Optional[np.ndarray]] = \
            [None] * self.slots
        self.stats = {"prefix_lookups": 0, "prefix_hits": 0,
                      "prefix_hit_tokens": 0, "cow_copies": 0,
                      "tree_evictions": 0}
        # the RESIDENT device footprint (the number the occupancy gauges
        # are about): paged leaves at pool scale + the per-slot recurrent
        # leaves — not slots x worst-case length
        ratio = self.num_blocks / float(self.slots * self.seq_blocks)
        self.cache_bytes = 0
        for n, s in shapes.items():
            bytes_ = int(np.prod(s.shape)) * s.dtype.itemsize
            _, sax = self.leaf_info[n]
            self.cache_bytes += int(bytes_ * ratio) if sax is not None \
                else bytes_
        # recompose with the block tables on top of the plain slots
        self.engine = Engine(self.model_w, self.mesh,
                             paged=(self.block_tokens, self.num_blocks))

    # -- block bookkeeping ---------------------------------------------------

    def _alloc_block(self, slot: int) -> int:
        """One block for ``slot``: free list first, then LRU eviction of
        refcount-0 tree leaves.  Reservations made at admission guarantee
        this succeeds for admitted requests."""
        while self.pool.free_count == 0:
            if self.tree is None or not self.tree.evict_lru(self.pool):
                raise RuntimeError(
                    "KV block pool exhausted with nothing evictable — "
                    "admission reservations should have prevented this")
            self.stats["tree_evictions"] += 1
        b = self.pool.alloc()
        self._owned[slot].add(b)
        if self._reserved[slot] > 0:
            self._reserved[slot] -= 1
            self.pool.unreserve(1)
        return b

    def _free_slot_blocks(self, slot: int) -> None:
        """Drop the slot's references.  Shared blocks deref (the parent /
        tree copy lives on); private blocks return to the free list unless
        they were promoted into the radix tree, where they stay as
        refcount-0 reusable cache.  Exactly the non-shared, non-promoted
        count lands back on the free list (tests pin it)."""
        for b in self._shared[slot]:
            if self.pool.deref(b) == 0 and not (self.tree is not None
                                                and self.tree.holds(b)):
                self.pool.reclaim(b)
        self._shared[slot] = []
        for b in self._owned[slot]:
            if self.pool.deref(b) == 0 and not (self.tree is not None
                                                and self.tree.holds(b)):
                self.pool.reclaim(b)
        self._owned[slot] = set()
        self.pool.unreserve(self._reserved[slot])
        self._reserved[slot] = 0
        self.rtable[slot, :] = self.SENTINEL
        self.wtable[slot, :] = self.SENTINEL
        self._keep_len[slot] = 0
        self._promo[slot] = None
        self._prompt_toks[slot] = None

    def _blocks_needed(self, prompt_len: int, end: int, toks) -> int:
        """Worst-case private blocks a request can come to own: blocks
        through its last written row, minus fully-shared ones."""
        if end <= 1:
            return 0
        shared_full = 0
        if self.tree is not None and prompt_len > 1:
            full, _, _ = self.tree.lookup(toks[:prompt_len - 1])
            shared_full = len(full)
        return max(0, (end - 1) // self.block_tokens + 1 - shared_full)

    # -- scheduler surface ---------------------------------------------------

    def can_admit(self, req) -> bool:
        """The scheduler's fits-gate: False keeps the request QUEUED (the
        slot-exhaustion semantics, extended to block exhaustion) instead
        of failing it."""
        toks = np.asarray(req.toks, np.int64).reshape(-1)[:self.seq - 1]
        need = self._blocks_needed(len(toks), req.end_pos(self.seq), toks)
        evictable = (self.tree.evictable_count(self.pool)
                     if self.tree is not None else 0)
        return self.pool.available(evictable) >= need

    def admit(self, slot: int, req) -> None:
        super().admit(slot, req)
        self._free_slot_blocks(slot)  # defensive: release() already ran
        toks = np.asarray(req.toks, np.int64).reshape(-1)[:self.seq - 1]
        plen = len(toks)
        end = int(self.end_pos[slot])
        need = self._blocks_needed(plen, end, toks)
        self.pool.reserve(need)
        self._reserved[slot] = need
        self._prompt_toks[slot] = toks
        full_nodes: typing.List[_Node] = []
        partial, depth = None, 0
        if self.tree is not None and plen > 1:
            # match at most plen-1 tokens: the decode must still run at
            # least one step (reading the last prompt token) to generate,
            # and capping here keeps every shared row child-valid
            full_nodes, partial, depth = self.tree.lookup(toks[:plen - 1])
            self.stats["prefix_lookups"] += 1
        shared_len = len(full_nodes) * self.block_tokens + depth
        for bi, node in enumerate(full_nodes):
            self.pool.addref(node.block)
            self._shared[slot].append(node.block)
            self.rtable[slot, bi] = node.block
            self.wtable[slot, bi] = self.SENTINEL  # read-only: never written
        if depth > 0:
            # copy-on-write at the divergence point: read the shared parent
            # block, write a fresh private one — the chunk's gather/scatter
            # round-trip performs the copy, the parent stays bit-unchanged
            bi = len(full_nodes)
            self.pool.addref(partial.block)
            self._shared[slot].append(partial.block)
            own = self._alloc_block(slot)
            self.rtable[slot, bi] = partial.block
            self.wtable[slot, bi] = own
            self.stats["cow_copies"] += 1
        if shared_len:
            self.stats["prefix_hits"] += 1
            self.stats["prefix_hit_tokens"] += shared_len
        self._keep_len[slot] = shared_len
        self.q[slot] = shared_len  # prefill skipped over the shared span
        self._promo[slot] = (full_nodes[-1] if full_nodes else None,
                             len(full_nodes))

    def release(self, slot: int) -> None:
        super().release(slot)
        self._free_slot_blocks(slot)

    # -- dispatch ------------------------------------------------------------

    def _ensure_blocks(self, steps: int) -> None:
        """Map private blocks through every live slot's write extent for
        this chunk (incremental allocation — in-use blocks track live
        tokens, not slots x worst-case)."""
        for s in range(self.slots):
            end = int(min(self.end_pos[s], self.seq))
            if end <= 0:
                continue
            hi = min(int(self.q[s]) + int(steps), end - 1)
            for bi in range(hi // self.block_tokens + 1):
                if self.rtable[s, bi] == self.SENTINEL:
                    b = self._alloc_block(s)
                    self.rtable[s, bi] = b
                    self.wtable[s, bi] = b

    def _promote_prompt_blocks(self) -> None:
        """Insert fully-walked prompt blocks into the radix tree so future
        admissions can hit them.  A block is promotable once every row in
        it has been written (q past its end) and its span lies entirely
        within the prompt (rows derived from known tokens, not generated
        ones)."""
        if self.tree is None:
            return
        bt = self.block_tokens
        for s in range(self.slots):
            if self._promo[s] is None or int(self.end_pos[s]) <= 0:
                continue
            node, bi = self._promo[s]
            toks = self._prompt_toks[s]
            plen = 0 if toks is None else len(toks)
            q = int(self.q[s])
            while (bi + 1) * bt <= min(plen, q):
                block = int(self.wtable[s, bi])
                if block == self.SENTINEL:
                    break  # shared span (shouldn't happen past the cursor)
                key = tuple(int(t) for t in toks[bi * bt:(bi + 1) * bt])
                node = self.tree.insert(node, key, block)
                bi += 1
            self._promo[s] = (node, bi)

    def dispatch(self, steps: int) -> np.ndarray:
        jnp = self._jnp
        self._ensure_blocks(steps)
        phase = ("init" if self._carry is None else
                 "admit" if self._admit_mask.any() else "plain")
        fn = self.engine.step(phase)
        fargs = (jnp.asarray(self.top_k), jnp.asarray(self.top_p),
                 jnp.asarray(self.rep))
        if phase == "init":
            seen = jnp.zeros((self.slots, self.params_w.vocab_size),
                             jnp.float32)
            carry = (jnp.zeros(self.slots, jnp.int32),
                     jnp.asarray(self._token_host), self._key0, seen)
        else:
            carry = self._carry
        admit_args = ()
        if phase != "plain":
            admit_args = (jnp.asarray(self._admit_mask),
                          jnp.asarray(self._admit_rows),
                          jnp.asarray(self._keep_len))
        out = fn(self.variables, jnp.asarray(self.ipb), jnp.asarray(self.tb),
                 jnp.asarray(self.end_pos), jnp.int32(int(steps)), fargs,
                 admit_args, jnp.asarray(self.rtable),
                 jnp.asarray(self.wtable), carry)
        q, token_x = out[0], out[1]
        self._carry = out
        self._token_host = np.asarray(token_x)
        self.q = np.asarray(q).astype(np.int64)
        self._admit_mask[:] = False
        # the write-back landed: from now on read every written block from
        # its private copy (this is what completes a COW — the next gather
        # must see the child's rows, not the parent's)
        written = self.wtable != self.SENTINEL
        self.rtable[written] = self.wtable[written]
        self._promote_prompt_blocks()
        return self.q

    def reset(self) -> None:
        """Failed-dispatch recovery: the donated carry (pool included) is
        gone, so every block mapping and the whole radix cache with it."""
        super().reset()
        self.pool = BlockPool(self.num_blocks)
        if self.tree is not None:
            self.tree.clear()
        self.rtable[:, :] = self.SENTINEL
        self.wtable[:, :] = self.SENTINEL
        self._keep_len[:] = 0
        self._owned = [set() for _ in range(self.slots)]
        self._shared = [[] for _ in range(self.slots)]
        self._reserved = [0] * self.slots
        self._promo = [None] * self.slots
        self._prompt_toks = [None] * self.slots

    # -- KV transfer (infer/kv_transfer.py, docs/SERVING.md 'Disaggregated
    # tier'): host handles on the donated carry's pool leaves, so block
    # streaming between replicas reads/writes them WITHOUT a new jit site

    def transfer_pools(self) -> typing.Optional[dict]:
        """``{poolset: (pools_dict, leaf_info)}`` of the live carry's
        block-pool leaves, or None before the first dispatch (the pools
        are built inside the donated init trace)."""
        if self._carry is None:
            return None
        # paged carry layout: (q, token_x, pools, key, seen)
        return {"target": (self._carry[2], self.leaf_info)}

    def set_transfer_pools(self, poolsets: dict) -> None:
        """Swap updated pool leaves back into the carry (eager ``.at[]``
        writes happened outside the donated programs)."""
        carry = list(self._carry)
        carry[2] = poolsets["target"]
        self._carry = tuple(carry)

    # -- observability -------------------------------------------------------

    def pool_stats(self) -> dict:
        """The /metrics block gauges (docs/OBSERVABILITY.md): occupancy
        that proves device KV memory tracks live tokens, plus the sharing
        economics (hits, shared tokens, COW copies, evictions)."""
        cached = (self.tree.evictable_count(self.pool)
                  if self.tree is not None else 0)
        return {
            "blocks_total": self.num_blocks,
            "blocks_free": self.pool.free_count,
            "blocks_in_use": self.pool.live_count,
            "blocks_cached": cached,
            "blocks_reserved": self.pool.reserved_total,
            "block_tokens": self.block_tokens,
            "sharing": self.sharing,
            **self.stats,
        }


# ------------------------------------------------- the composed deployment

class SpecPagedEngineExecutor(SpecEngineExecutor, PagedEngineExecutor):
    """Spec-on-paged: draft-and-verify running over the block pool — the
    ``spec_paged_chunk_step`` composition, assembled from the two
    components rather than written as a fourth program.

    The draft model's cache leaves page onto the SAME block tables as the
    target's (one logical block space, two physical pools): a draft KV row
    is deterministic in tokens+position exactly like a target row, so a
    prefix-hit admission resumes the draft from the shared span too, COW
    divergence copies both pools through the same gather/scatter
    round-trip, and rejected draft rows in both pools self-heal
    left-to-right before the next round reads them (the rollback-by-
    overwrite argument, unchanged).  Because the spec probe already refuses
    sequence-recurrent caches (both models), every leaf of both pools is
    pageable — the composed deployment always has prefix sharing.

    Construction raises ``NotImplementedError`` on either component's
    refusal signal (draft geometry, recurrent caches, block divisibility)
    so ``auto`` knobs can fall back component-wise; greedy parity with the
    plain slot engine through prefix-hit admission, mid-draft COW
    divergence, and total-rejection rounds is pinned token-for-token by
    tests/spec_paged_test.py."""

    def __init__(self, interface, slots: int, draft,
                 seed: typing.Optional[int] = None,
                 draft_tokens: typing.Optional[int] = None,
                 min_accept_rate: typing.Optional[float] = None,
                 block_tokens: typing.Optional[int] = None,
                 pool_blocks: typing.Optional[int] = None):
        # the two init halves run in sequence, mirroring the carry: the
        # paged base builds pool/tree/tables (and recomposes the Engine
        # with the block tables), then the spec half stacks the draft pool
        # + accept state on top and recomposes again
        PagedEngineExecutor.__init__(self, interface, slots, seed=seed,
                                     block_tokens=block_tokens,
                                     pool_blocks=pool_blocks)
        self._init_spec(draft, draft_tokens, min_accept_rate)

    def _draft_leaf_info(self) -> typing.Dict[str, tuple]:
        """Leaf classification for the DRAFT pool (its cache geometry,
        not the target's), computed once — kv_transfer streams both pools
        through the shared block tables."""
        cached = getattr(self, "_draft_leaf_info_cache", None)
        if cached is None:
            from .sampler import decode_cache_shapes
            probe = np.zeros((self.slots, self.seq, self.tps), np.int32)
            dshapes = decode_cache_shapes(self.draft_model_w,
                                          self.draft_variables, probe)
            cached = classify_cache_leaves(dshapes, self.seq)
            self._draft_leaf_info_cache = cached
        return cached

    def transfer_pools(self) -> typing.Optional[dict]:
        if not self._spec_enabled:
            return PagedEngineExecutor.transfer_pools(self)
        if self._carry is None:
            return None
        # spec-paged carry layout: (token_x, pools, dpools, key, seen)
        return {"target": (self._carry[1], self.leaf_info),
                "draft": (self._carry[2], self._draft_leaf_info())}

    def set_transfer_pools(self, poolsets: dict) -> None:
        if not self._spec_enabled:
            return PagedEngineExecutor.set_transfer_pools(self, poolsets)
        carry = list(self._carry)
        carry[1] = poolsets["target"]
        carry[2] = poolsets["draft"]
        self._carry = tuple(carry)

    def dispatch(self, steps: int) -> np.ndarray:
        """Acceptance-aware dispatch over the block pool: verify rounds
        like the spec executor, block-table maintenance like the paged one.
        Once self-disabled, ``_to_plain_carry`` has recomposed the Engine
        down to the paged composition and every dispatch delegates there."""
        if not self._spec_enabled:
            return PagedEngineExecutor.dispatch(self, steps)
        jnp = self._jnp
        rounds = max(1, -(-int(steps) // (self.k + 1)))
        for _ in range(rounds):
            # a verify round writes at most k+1 rows past each slot's
            # position: map private blocks through that extent first
            self._ensure_blocks(self.k + 1)
            phase = ("init" if self._carry is None else
                     "admit" if self._admit_mask.any() else "plain")
            fn = self.engine.step(phase)
            if self._dev_args is None:
                self._dev_args = (jnp.asarray(self.ipb),
                                  jnp.asarray(self.tb),
                                  jnp.asarray(self.end_pos),
                                  (jnp.asarray(self.top_k),
                                   jnp.asarray(self.top_p),
                                   jnp.asarray(self.rep)),
                                  jnp.asarray(self._spec_mask))
            ipb_d, tb_d, end_d, fargs, mask_d = self._dev_args
            if phase == "init":
                seen = jnp.zeros((self.slots, self.params_w.vocab_size),
                                 jnp.float32)
                carry = (jnp.asarray(self._token_host), self._key0, seen)
            else:
                carry = self._carry
            admit_args = ()
            if phase != "plain":
                admit_args = (jnp.asarray(self._admit_mask),
                              jnp.asarray(self._admit_rows),
                              jnp.asarray(self._keep_len))
            out = fn(self.variables, self.draft_variables,
                     jnp.asarray(self.q.astype(np.int32)),
                     ipb_d, tb_d, end_d, fargs, mask_d,
                     jnp.asarray(self._fix_tok),
                     jnp.asarray(self._fix_mask),
                     jnp.asarray(self._seen_lo), admit_args,
                     jnp.asarray(self.rtable), jnp.asarray(self.wtable),
                     carry)
            self._carry = out[:5]
            # np.array, not asarray: the accept loop WRITES corrections
            self._token_host = np.array(out[0])
            self._admit_mask[:] = False
            # the write-back landed: read every written block from its
            # private copy from now on (completes COW for BOTH pools —
            # they share the tables)
            written = self.wtable != self.SENTINEL
            self.rtable[written] = self.wtable[written]
            self._accept_round(np.asarray(out[5]))
            self._promote_prompt_blocks()
            if not self._spec_enabled:
                break  # recomposed to paged mid-dispatch: it takes over
            if not np.any((self.end_pos > 0)
                          & (self.q < self.end_pos - 1)):
                break  # every live slot reached its end
        return self.q
