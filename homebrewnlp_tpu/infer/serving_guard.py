"""Serving reliability layer (docs/RELIABILITY.md 'Serving').

PR 1 gave *training* an engineered failure story; this module gives the
REST serving path the same treatment, as five cooperating mechanisms used by
``infer/rest_api.py``:

1. **Admission control** — a bounded pending-request budget
   (``serve_queue_limit``): when the IPC queue is full the HTTP child
   answers 429 + ``Retry-After`` immediately instead of enqueueing, and
   ``validate_request`` rejects oversized/overlong/miscapped requests with
   400 at the HTTP edge, before they cost a device call.
2. **Per-request deadlines** — clients may pass ``timeout_s`` (capped by
   ``serve_request_deadline_s``); the deadline rides the request tuple into
   batch assembly, expired requests are shed *and answered* with 504, and
   the child's own poll gives up at the same deadline — no accepted request
   ever goes unanswered.
3. **Circuit breaker** — ``CircuitBreaker``: after
   ``serve_breaker_threshold`` consecutive decode failures requests
   fast-fail with 503 + ``Retry-After`` for ``serve_breaker_cooldown_s``,
   then a single probe half-opens it.  The device loop owns the breaker;
   its state is mirrored into shared IPC state so the HTTP child fast-fails
   without touching the device loop.
4. **Supervision + liveness** — the device loop heartbeats into shared
   state every poll; ``child_health``/``child_ready`` build the
   ``/health``/``/ready`` payloads in the HTTP child directly, so health
   checks answer even when the device loop is wedged in a decode.
5. **Fault injection** — ``utils.fault_injection.FaultyInterface`` drives
   all of the above deterministically in tests/serving_robustness_test.py.

Deliberately dependency-light (stdlib only): everything here must be
importable from the spawned HTTP child subprocess without touching jax.

Clock discipline: all elapsed-time arithmetic uses ``time.monotonic()``.
Deadlines DO cross the child->device-loop process boundary, which is safe
because both processes live on one host and CLOCK_MONOTONIC is system-wide
on every platform we serve from (Linux; also macOS/Windows equivalents).
"""
from __future__ import annotations

import time
import typing


class HTTPStatusError(Exception):
    """A response with an explicit HTTP status (and optional Retry-After),
    raised by dispatch/validation and rendered by the HTTP server layer."""

    def __init__(self, status: int, payload: typing.Dict[str, typing.Any],
                 retry_after: typing.Optional[float] = None):
        super().__init__(payload.get("error", str(status)))
        self.status = int(status)
        self.payload = payload
        self.retry_after = retry_after


def _bad_request(msg: str) -> typing.NoReturn:
    raise HTTPStatusError(400, {"error": msg, "code": "bad_request"})


def serve_config(params) -> typing.Dict[str, typing.Any]:
    """The serving knobs as a plain picklable dict — the HTTP child
    subprocess gets this instead of the full ModelParameter (which carries
    jnp dtypes and derived Dim objects it must never import)."""
    seq = (int(getattr(params, "sequence_length", 0))
           // max(1, int(getattr(params, "token_patch_size", 1) or 1)))
    return {
        "queue_limit": int(getattr(params, "serve_queue_limit", 64) or 0),
        "deadline_s": float(getattr(params, "serve_request_deadline_s", 120.0)),
        "max_body_bytes": int(getattr(params, "serve_max_body_bytes", 1 << 20) or 0),
        # 0 = cap off: over-asks clamp to the sequence like they always
        # did (rejecting them at the default config would break existing
        # clients that expect server-side clamping)
        "max_response_tokens": int(getattr(params, "serve_max_response_tokens",
                                           0) or 0),
        "seq_tokens": seq,
        "vocab_size": int(getattr(params, "vocab_size", 256)),
        "serve_batch_size": int(getattr(params, "serve_batch_size", 1) or 1),
        "hb_stale_s": float(getattr(params, "serve_heartbeat_stale_s", 0.0)
                            or 0.0),
    }


def validate_request(path: str, body, cfg: typing.Dict[str, typing.Any]):
    """Reject requests that cannot possibly succeed with 400 at the HTTP
    edge, before they cost an IPC round-trip and a device call: non-object
    bodies, prompts past the sequence capacity, ``max_tokens`` above the
    server cap, and malformed ``timeout_s``.

    /completion prompt length is only checkable here for the byte-level
    tokenizer (vocab <= 256: one token per UTF-8 byte); BPE prompts are
    still truncation-flagged by the device loop (satellite: ``truncated``)."""
    if not isinstance(body, dict):
        _bad_request("JSON object body required")
    seq = int(cfg.get("seq_tokens", 0) or 0)
    if path == "/token_completion":
        toks = body.get("tokens", [])
        if not isinstance(toks, (list, tuple)):
            _bad_request("tokens must be a list of ints")
        if seq and len(toks) > seq:
            _bad_request(f"prompt of {len(toks)} tokens exceeds the "
                         f"{seq}-token sequence capacity")
    if path in ("/completion", "/encode"):
        prompt = body.get("prompt", "")
        if not isinstance(prompt, str):
            _bad_request("prompt must be a string")
    if path == "/completion":
        prompt = body.get("prompt", "")
        if seq and int(cfg.get("vocab_size", 257)) <= 256:
            n = len(prompt.encode("utf-8", "replace"))
            if n > seq:
                _bad_request(f"prompt of {n} byte-tokens exceeds the "
                             f"{seq}-token sequence capacity")
    if path in ("/completion", "/token_completion"):
        mt = body.get("max_tokens")
        if mt is not None:
            try:
                mt = int(mt)
            except (TypeError, ValueError, OverflowError):
                # OverflowError: json.loads accepts the Infinity literal,
                # and int(float('inf')) overflows — still a client error
                _bad_request(f"max_tokens must be an int, got {mt!r}")
            if mt < 0:
                _bad_request(f"max_tokens must be >= 0, got {mt}")
            cap = int(cfg.get("max_response_tokens", 0) or 0)
            if cap and mt > cap:
                _bad_request(f"max_tokens={mt} above the server cap of {cap}")
    ts = body.get("timeout_s")
    if ts is not None:
        try:
            ts = float(ts)
        except (TypeError, ValueError):
            _bad_request(f"timeout_s must be a number, got {ts!r}")
        if ts <= 0:
            _bad_request(f"timeout_s must be > 0, got {ts}")


def request_deadline_s(body, cfg: typing.Dict[str, typing.Any]) -> float:
    """Effective per-request deadline: the client's ``timeout_s`` capped by
    ``serve_request_deadline_s`` (which is also the default)."""
    cap = float(cfg.get("deadline_s", 120.0))
    ts = body.get("timeout_s") if isinstance(body, dict) else None
    if ts is None:
        return cap
    try:
        ts = float(ts)
    except (TypeError, ValueError):
        return cap
    return min(ts, cap) if ts > 0 else cap


def poll_delay(delay: float, start: float = 0.002, ceiling: float = 0.05,
               growth: float = 1.5) -> float:
    """Adaptive response-poll backoff: each Manager-dict membership probe is
    an IPC round-trip to the Manager process, so N slow concurrent requests
    polling at a fixed 2 ms hammer it with 500*N probes/sec.  Start at 2 ms
    (snappy fast requests) and grow toward ~50 ms (cheap slow ones)."""
    return min(max(delay, start) * growth, ceiling)


#: numeric encoding of the breaker state for the Prometheus gauge
BREAKER_STATES = {"closed": 0, "half_open": 1, "open": 2}
BREAKER_GAUGE = "hbnlp_serve_breaker_state"


def state_metrics(state, queue_depth: int) -> dict:
    """The serving_guard counters that live in shared IPC state, re-shaped
    as a telemetry snapshot so ``GET /metrics`` exports them as first-class
    series (docs/OBSERVABILITY.md).  Built child-side from the state dict —
    never crossing the device loop."""

    def scalar(kind: str, help_: str, value) -> dict:
        return {"kind": kind, "help": help_, "labels": (), "buckets": [],
                "series": {(): float(value or 0)}}

    return {
        "hbnlp_serve_queue_depth": scalar(
            "gauge", "queued + in-decode completion requests", queue_depth),
        BREAKER_GAUGE: scalar(
            "gauge", "circuit breaker state: 0=closed 1=half_open 2=open",
            BREAKER_STATES.get(state.get("breaker", "closed"), 0)),
        "hbnlp_serve_decode_calls_total": scalar(
            "counter", "decode calls issued by the device loop",
            state.get("decode_calls", 0)),
        "hbnlp_serve_decode_failures_total": scalar(
            "counter", "decode calls that raised (breaker input)",
            state.get("decode_failures", 0)),
        "hbnlp_serve_breaker_trips_total": scalar(
            "counter", "times the circuit breaker opened",
            state.get("breaker_trips", 0)),
        "hbnlp_serve_child_restarts_total": scalar(
            "counter", "HTTP child subprocess relaunches",
            state.get("child_restarts", 0)),
    }


class CircuitBreaker:
    """closed -> open after ``threshold`` CONSECUTIVE decode failures; while
    open, requests fast-fail (503) for ``cooldown_s``; then ``tick()`` moves
    to half_open, where a single probe request decides: success recloses,
    failure reopens for another cooldown.  ``threshold <= 0`` disables the
    breaker entirely (always closed).  The clock is injectable so tests
    drive the full cycle with zero wall-clock sleeps."""

    def __init__(self, threshold: int, cooldown_s: float,
                 clock: typing.Callable[[], float] = time.monotonic):
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self.state = "closed"
        self.failures = 0       # consecutive decode failures
        self.open_until = 0.0
        self.opened = 0         # times the breaker has tripped (ops counter)

    def tick(self) -> str:
        if self.state == "open" and self.clock() >= self.open_until:
            self.state = "half_open"
        return self.state

    def record_failure(self):
        if self.threshold <= 0:
            return
        self.failures += 1
        if self.state == "open":
            # already open (e.g. per-row retries of the batch that tripped
            # it): re-tripping would inflate the `opened` ops counter and
            # restart the cooldown from the last straggler failure
            return
        if self.state == "half_open" or self.failures >= self.threshold:
            self.state = "open"
            self.open_until = self.clock() + self.cooldown_s
            self.opened += 1
            # breaker trips are incident anchors: into the flight recorder
            # (docs/OBSERVABILITY.md 'Flight recorder'), never the hot path
            from ..telemetry import events as _flight
            _flight.record("breaker", state="open", failures=self.failures,
                           trips=self.opened)

    def record_success(self):
        self.failures = 0
        if self.state != "closed":
            # a successful probe (or a straggler decode finishing cleanly
            # after the trip) is direct evidence the device is healthy again
            self.state = "closed"
            from ..telemetry import events as _flight
            _flight.record("breaker", state="closed", trips=self.opened)

    def retry_after(self) -> float:
        return max(0.0, self.open_until - self.clock())


class ServingGuard:
    """Device-loop-side reliability state: the breaker plus the decode
    failure counter it reads, and the publisher that mirrors both — with a
    liveness heartbeat — into the shared IPC state the HTTP child serves
    ``/health``/``/ready`` and fast-fail decisions from."""

    def __init__(self, params=None, threshold: typing.Optional[int] = None,
                 cooldown_s: typing.Optional[float] = None,
                 clock: typing.Callable[[], float] = time.monotonic):
        if threshold is None:
            threshold = int(getattr(params, "serve_breaker_threshold", 0) or 0)
        if cooldown_s is None:
            cooldown_s = float(getattr(params, "serve_breaker_cooldown_s", 30.0))
        self.breaker = CircuitBreaker(threshold, cooldown_s, clock)
        self.clock = clock
        self.decode_failures = 0

    def record_decode_success(self):
        self.breaker.record_success()

    def record_decode_failure(self):
        self.decode_failures += 1
        self.breaker.record_failure()

    def publish(self, state, interface=None, restarts: int = 0):
        # one .update call = one IPC round-trip (per-key assignment would be
        # one each); runs once per device-loop poll.  The registry snapshot
        # rides the same update: it is how GET /metrics in the HTTP child
        # sees the device loop's decode/queue-wait histograms WITHOUT ever
        # crossing the device loop (same invariant as /health)
        breaker_state = self.breaker.tick()
        try:
            from ..telemetry import registry as _reg, snapshot as _snapshot
            _reg().gauge(BREAKER_GAUGE,
                         "circuit breaker state: 0=closed 1=half_open 2=open"
                         ).set(BREAKER_STATES.get(breaker_state, 0))
            # chief-only on a multi-host serving deployment: every host runs
            # the same device loop over the SAME global computation, so a
            # per-host /metrics scrape summed downstream would multiply
            # every decode/token counter by the process count
            # (docs/DISTRIBUTED.md).  Single-process (the normal serving
            # topology) is unaffected.
            import jax
            snap = _snapshot() if jax.process_index() == 0 else {}
        except Exception:
            snap = {}
        state.update(hb=self.clock(),
                     breaker=breaker_state,
                     breaker_open_until=self.breaker.open_until,
                     breaker_trips=self.breaker.opened,
                     decode_failures=self.decode_failures,
                     decode_calls=int(getattr(interface, "decode_calls", 0) or 0),
                     child_restarts=int(restarts),
                     metrics=snap)


def child_health(state, queue_depth: int, cfg: typing.Dict[str, typing.Any],
                 clock: typing.Callable[[], float] = time.monotonic) -> dict:
    """Liveness payload, built ENTIRELY from shared state + the queue proxy:
    answering must never cross the device loop, or health checks block
    exactly when the server is sick.

    With ``serve_heartbeat_stale_s`` > 0, a heartbeat older than the
    threshold flips ``status`` to "stale" (served as HTTP 503) so an
    orchestrator's status-code-only liveness probe restarts a permanently
    wedged device loop.  Off by default: a legitimately long decode also
    ages the heartbeat, so the operator must pick a threshold above their
    worst-case decode."""
    hb = state.get("hb")
    age = round(clock() - hb, 3) if hb is not None else None
    stale_after = float(cfg.get("hb_stale_s", 0) or 0)
    stale = stale_after > 0 and age is not None and age > stale_after
    return {"status": "stale" if stale else "ok",
            "heartbeat_age_s": age,
            "breaker": state.get("breaker", "closed"),
            "queue_depth": int(queue_depth),
            "decode_calls": int(state.get("decode_calls", 0) or 0),
            "decode_failures": int(state.get("decode_failures", 0) or 0),
            "breaker_trips": int(state.get("breaker_trips", 0) or 0),
            "child_restarts": int(state.get("child_restarts", 0) or 0),
            "serve_batch_size": int(cfg.get("serve_batch_size", 1)),
            "decode_path": state.get("decode_path"),
            # which serving engine the device loop resolved (continuous
            # slot-pool vs batch-to-completion) and the pool width —
            # published once at serve() start, ops surface like decode_path
            "engine": state.get("engine")}


def child_ready(state, queue_depth: int, cfg: typing.Dict[str, typing.Any]
                ) -> typing.Tuple[bool, dict]:
    """Readiness: model loaded AND breaker not open AND queue below the
    watermark (``serve_queue_limit``).  Distinct from /health: a load
    balancer drains a not-ready replica but does not restart it.

    half_open deliberately reports READY: reclosing requires a real
    completion request to serve as the probe, and a readiness-honoring load
    balancer would otherwise never route one — leaving the replica drained
    forever after the device recovered."""
    reasons = []
    if not state.get("model_loaded"):
        reasons.append("model not loaded")
    breaker = state.get("breaker", "closed")
    if breaker == "open":
        reasons.append("circuit breaker open")
    watermark = int(cfg.get("queue_limit", 0) or 0)
    if watermark and queue_depth >= watermark:
        reasons.append(f"queue depth {queue_depth} at/above the "
                       f"{watermark}-request watermark")
    payload = {"ready": not reasons, "breaker": breaker,
               "queue_depth": int(queue_depth)}
    if reasons:
        payload["reasons"] = reasons
    return not reasons, payload
