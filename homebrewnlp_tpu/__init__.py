"""TPU-native rebuild of HomebrewNLP-MTF (see SURVEY.md)."""

from .config import BlockArgs, BlockConfig, LearningRateConfig, ModelParameter  # noqa: F401
