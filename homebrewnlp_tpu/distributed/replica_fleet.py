"""Replica fleet: the process half of the multi-replica serving tier.

``infer/router.py`` dispatches; this module owns N replica PROCESSES, each
a full isolated serving deployment (``rest_api.serve``: its own device
loop, HTTP child, guard) of the same config on its own port.  It
generalizes two existing runtimes:

* the fan-out/monitor/relaunch loop follows ``scripts/run_manager.py``'s
  fleet semantics (PR 10) — dead replicas relaunch with bounded
  exponential backoff, and the crash budget RESETS after a replica stays
  up through a stability window (it bounds crash LOOPS, not lifetime
  crash count — the ``rest_api`` child-supervision rule);
* processes use the spawn context like the serving HTTP child (forking a
  multithreaded JAX parent can deadlock the child).

Each replica rebuilds the model from the config's ``_raw_config`` dict
(checkpoints restore through the same corruption-tolerant
``restore_latest_valid`` walk as single-replica serving), with
``serve_replicas`` forced to 0 inside the replica — a replica must never
recursively spawn its own tier.  The router's per-replica breaker handles
the WINDOW while a replica relaunches: its port refuses connections, the
breaker opens, dispatch skips it, and the probe recloses it once the
relaunched replica binds.
"""
from __future__ import annotations

import time
import typing


def install_replica_stop():
    """SIGTERM/SIGINT -> a stop event for ``rest_api.serve``: the fleet's
    ``terminate()`` then drains the replica's device loop cleanly (HTTP
    child + IPC Manager torn down) instead of orphaning its subprocesses
    — the default signal disposition kills the replica before its
    ``finally`` teardown runs."""
    import signal
    import threading

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:  # not the main thread (embedded/test use)
            break
    return stop


def _replica_main(cfg: dict, port: int, index: int):
    """Subprocess body: load the model, serve one isolated deployment."""
    from ..config import ModelParameter
    from ..infer.interface import InterfaceWrapper
    from ..infer.rest_api import serve
    from ..run.modes import _load_model

    stop = install_replica_stop()
    params = ModelParameter(dict(cfg), serve_replicas=0)
    if getattr(params, "trace_requests", False) and params.model_path:
        # replica-indexed blackbox tag BEFORE serve() (which would default
        # to "serve"): the device loop's event file becomes
        # blackbox_r<i>.jsonl, its HTTP child blackbox_r<i>_http.jsonl —
        # forensics then shows which replica a trace crossed
        from ..telemetry import events as _flight
        _flight.configure(params.model_path, f"r{index}",
                          capacity=getattr(params,
                                           "telemetry_blackbox_events",
                                           4096))
    params, model, variables, mesh = _load_model(params)
    interface = InterfaceWrapper(params, model, variables, mesh=mesh)
    print(f"[replica {index}] serving on :{port}", flush=True)
    serve(params, interface, port=port, isolate=True, stop=stop)


class ReplicaFleet:
    """Spawn + supervise N replica serving processes on consecutive ports.

    ``poll()`` (called from the tier's main loop) relaunches dead replicas
    with bounded exponential backoff per replica; ``stop()`` terminates
    the fleet.  ``target`` is injectable for tests (a device-free stand-in
    for ``_replica_main``)."""

    def __init__(self, params, n: int, base_port: int,
                 max_restarts: typing.Optional[int] = None,
                 restart_backoff_s: typing.Optional[float] = None,
                 target: typing.Callable = _replica_main,
                 classes: typing.Optional[typing.Sequence[str]] = None):
        import multiprocessing as mp

        self.cfg = dict(getattr(params, "_raw_config", params))
        self.n = int(n)
        self.base_port = int(base_port)
        self.target = target
        #: per-replica class for the disaggregated tier (docs/SERVING.md);
        #: rides each replica's cfg as ``serve_replica_class`` so the
        #: 3-arg spawn target (injectable in tests) stays unchanged
        self.classes = [str(c or "") for c in (classes or [])]
        if self.classes and len(self.classes) != self.n:
            raise ValueError(f"classes ({len(self.classes)}) must match "
                             f"replica count ({self.n})")
        self.max_restarts = int(
            getattr(params, "serve_child_max_restarts", 5) or 0
            if max_restarts is None else max_restarts)
        self.base_backoff = float(
            getattr(params, "serve_child_restart_backoff_s", 0.5)
            if restart_backoff_s is None else restart_backoff_s)
        self._ctx = mp.get_context("spawn")
        self._procs: typing.List[typing.Optional[typing.Any]] = [None] * n
        self._restarts = [0] * n
        self._backoff = [self.base_backoff] * n
        self._next_spawn = [0.0] * n
        self._up_since = [0.0] * n
        self.stability_window_s = 60.0

    def port(self, index: int) -> int:
        return self.base_port + int(index)

    def _spawn(self, index: int) -> None:
        # NOT daemonic: a replica spawns its own Manager + HTTP child, and
        # daemonic processes are forbidden children.  stop() (wired to the
        # mode's SIGTERM/SIGINT drain) terminates the fleet instead.
        cfg = self.cfg
        if self.classes:
            cfg = dict(cfg)
            cfg["serve_replica_class"] = self.classes[index]
            # a replica inherits the tier config verbatim; its own class
            # replaces the topology knob (a replica never spawns a tier)
            cfg.pop("serve_replica_classes", None)
        p = self._ctx.Process(
            target=self.target,
            args=(cfg, self.port(index), index), daemon=False)
        p.start()
        self._procs[index] = p
        self._up_since[index] = time.monotonic()

    def start(self) -> None:
        for i in range(self.n):
            self._spawn(i)

    def poll(self) -> None:
        """Relaunch dead replicas whose backoff has elapsed.  A replica
        out of restart budget raises — a fleet silently shrinking to zero
        is worse than a loud failure (the router keeps serving the
        surviving replicas until then)."""
        now = time.monotonic()
        for i, p in enumerate(self._procs):
            if p is None or p.is_alive():
                if (p is not None and self._restarts[i]
                        and now - self._up_since[i]
                        > self.stability_window_s):
                    # survived the stability window: the relaunch recovered
                    self._restarts[i] = 0
                    self._backoff[i] = self.base_backoff
                continue
            if self._next_spawn[i] == 0.0:
                self._restarts[i] += 1
                if self._restarts[i] > self.max_restarts:
                    raise RuntimeError(
                        f"replica {i} exited (code {p.exitcode}) and "
                        f"{self.max_restarts} relaunches were exhausted")
                print(f"replica {i} died (code {p.exitcode}); relaunch "
                      f"{self._restarts[i]}/{self.max_restarts} in "
                      f"{self._backoff[i]:.2f}s", flush=True)
                self._next_spawn[i] = now + self._backoff[i]
                self._backoff[i] = min(self._backoff[i] * 2, 30.0)
            elif now >= self._next_spawn[i]:
                self._next_spawn[i] = 0.0
                self._spawn(i)

    def alive(self) -> int:
        return sum(1 for p in self._procs if p is not None and p.is_alive())

    def stop(self) -> None:
        for p in self._procs:
            if p is not None and p.is_alive():
                p.terminate()
        for p in self._procs:
            if p is not None:
                p.join(timeout=15.0)
                if p.is_alive():
                    # the drain is stuck (e.g. wedged mid-decode): escalate
                    # rather than leak the replica + its IPC children
                    p.kill()
                    p.join(timeout=5.0)
