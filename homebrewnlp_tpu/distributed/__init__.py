"""Multi-host runtime: slice-aware bootstrap, coordination-service
helpers, and async sharded checkpointing (docs/DISTRIBUTED.md).

The package turns the dryrun parallel strategies (scripts/pod_lowering.py,
analysis/mesh_audit.py) into a launchable multi-process runtime:

- ``bootstrap``        — ``jax.distributed.initialize`` wiring with explicit
                         env flags for the CPU multiprocess rig and standard
                         autodiscovery on TPU pods, a topology report, and
                         coordination-service barrier/KV helpers that never
                         touch the device path (safe from background
                         threads while the step loop runs collectives).
- ``async_checkpoint`` — double-buffered background checkpoint saver with a
                         step-tagged commit barrier, so a pod checkpoint
                         costs the step thread one host staging copy instead
                         of the full serialize+upload+barrier stall.
"""
from .bootstrap import (barrier, coordination_client, is_initialized,
                        kv_dir_get, kv_put, maybe_initialize, shutdown,
                        topology_report)

__all__ = ["maybe_initialize", "topology_report", "shutdown", "barrier",
           "coordination_client", "is_initialized", "kv_put", "kv_dir_get"]
