"""Elastic pod membership: KV heartbeat leases + shrink/grow support
(docs/DISTRIBUTED.md 'Elasticity', ROADMAP item 5).

PR 10 proved the two hard primitives — resume across a host-count change
is multiset-exact, and preemption relaunch is pod-wide — but the fleet
stayed rigid: a dead host needed a human and a fixed ``--num-processes``.
This module is the missing membership layer:

* **Worker side** (``ElasticAgent``, started by the train loop when
  ``elastic_training`` is on): a daemon thread publishes a heartbeat lease
  under a generation-numbered key in the coordination-service KV
  (``bootstrap.kv_put`` — gRPC to the coordinator, NO device collectives,
  so it keeps beating while the main thread runs jitted steps) and scans
  its peers' leases.  A peer whose lease stops advancing for
  ``elastic_lease_timeout_s`` (SIGKILLed host, wedged rank) — or a dead
  coordinator — is a MEMBERSHIP EVENT: the agent records it, writes a
  marker file naming the lapsed ranks, gives the main loop a short grace
  to exit through its own check (between steps), then force-exits the
  process with ``MEMBERSHIP_EXIT_CODE``.  Force-exit is deliberate: the
  main thread may already be wedged in a collective against the dead rank
  and can never finish; the freshest COMPLETE checkpoint on disk is the
  recovery point (an uncommitted async save stays invisible to
  ``restore_latest_valid`` — PR 10's torn-save semantics).

* **Chief mirror**: process 0's agent mirrors the lease table to
  ``<model_path>/elastic/leases.json`` through the fs seam, so the
  elastic controller (``scripts/run_manager.py --elastic``) — which is
  not a member of the jax cluster and cannot read the coordination KV —
  observes membership through the same shared storage the checkpoints
  ride.

* **Controller helpers** (no jax imports): marker/mirror readers and the
  exit-code classifier the controller uses to decide shrink vs crash.

Generation numbers: every fleet (re)launch is a new generation
(``HBNLP_GENERATION``, fresh coordinator port, fresh
``jax.distributed.initialize`` at the new world size).  Lease keys embed
the generation so a stale publisher from a dying generation can never
satisfy the next one's liveness scan.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
import typing

#: a survivor of a membership change exits with this code — resumable
#: from the freshest complete checkpoint at the surviving world size.
#: Distinct from 143 (graceful preemption, emergency checkpoint written):
#: a membership exit could NOT write a checkpoint (the pod lost a rank
#: mid-step), so the controller resumes from the last committed one.
#: The controller (scripts/run_manager.py --elastic) imports this module's
#: helpers directly — its top level is jax-free by design.
MEMBERSHIP_EXIT_CODE = 144

#: coordination-KV namespace for leases: ``hbnlp/elastic/g<gen>/p<pid>``
LEASE_PREFIX = "hbnlp/elastic/"


def generation() -> int:
    """This process's fleet generation (``HBNLP_GENERATION``, stamped by
    the elastic controller; 0 standalone)."""
    try:
        return int(os.environ.get("HBNLP_GENERATION", "0"))
    except ValueError:
        return 0


def elastic_dir(model_path: str) -> str:
    from ..utils import fs
    return fs.join(model_path, "elastic")


def lease_mirror_path(model_path: str) -> str:
    from ..utils import fs
    return fs.join(elastic_dir(model_path), "leases.json")


def membership_marker_path(model_path: str, gen: int) -> str:
    from ..utils import fs
    return fs.join(elastic_dir(model_path), f"membership_g{gen}.json")


def preempt_notice_path(model_path: str) -> str:
    """Cloud tooling (or an operator) announces an upcoming capacity loss
    by writing ``{"processes": [ranks]}`` here; the controller shrinks
    PROACTIVELY through the graceful 143 path (emergency checkpoint, no
    lost steps) instead of waiting for the lease to lapse."""
    from ..utils import fs
    return fs.join(elastic_dir(model_path), "preempt.json")


class ElasticAgent:
    """Per-process heartbeat lease + peer liveness scan.

    ``kv_put``/``kv_dir_get``/``clock``/``exit_fn`` are injectable so the
    state machine unit-tests without a jax cluster
    (tests/elastic_test.py)."""

    def __init__(self, model_path: str, process_index: int,
                 process_count: int, gen: typing.Optional[int] = None,
                 interval_s: float = 1.0, timeout_s: float = 10.0,
                 exit_grace_s: float = 3.0,
                 kv_put: typing.Optional[typing.Callable] = None,
                 kv_dir_get: typing.Optional[typing.Callable] = None,
                 clock: typing.Callable[[], float] = time.monotonic,
                 exit_fn: typing.Callable[[int], None] = os._exit,
                 on_event: typing.Optional[typing.Callable[[str], None]] = None,
                 pre_exit: typing.Optional[typing.Callable[[], None]] = None,
                 progress: typing.Optional[typing.Callable[[], int]] = None,
                 straggler_factor: float = 0.0,
                 on_straggler: typing.Optional[typing.Callable] = None,
                 recorder=None):
        from . import bootstrap
        from ..telemetry import events as _events
        self.model_path = model_path
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.gen = generation() if gen is None else int(gen)
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.exit_grace_s = float(exit_grace_s)
        self._kv_put = kv_put or bootstrap.kv_put
        self._kv_dir_get = kv_dir_get or bootstrap.kv_dir_get
        self._clock = clock
        self._exit = exit_fn
        self._on_event = on_event
        self._pre_exit = pre_exit
        #: host-side step mirror (the train loop updates a plain ref; the
        #: lease value publishes it so the chief's straggler detector sees
        #: every rank's progress without device work)
        self._progress = progress
        self.straggler_factor = float(straggler_factor)
        self._on_straggler = on_straggler
        self._recorder = recorder if recorder is not None \
            else _events.recorder()
        self._seq = 0
        self._stop = threading.Event()
        self._thread: typing.Optional[threading.Thread] = None
        #: peer -> (last seen seq, clock() when it last ADVANCED)
        self._peer_beats: typing.Dict[int, typing.Tuple[int, float]] = {}
        #: what the last scan SAW per peer (seq) — recorded into the flight
        #: recorder so forensics can order cross-process events causally
        self._last_seen: typing.Dict[int, int] = {}
        #: rank -> (step, clock() when the step last advanced) — all ranks
        #: incl. self, fed by the lease values' step field
        self._rank_steps: typing.Dict[int, typing.Tuple[int, float]] = {}
        #: rank -> last observed step-advance interval (straggler median)
        self._step_intervals: typing.Dict[int, float] = {}
        self._straggler_flagged: typing.Set[int] = set()
        #: rank -> clock() when first suspected (two-scan confirmation: a
        #: momentarily-stale lease right after a fleet-wide stall clears
        #: must not flag a healthy peer)
        self._straggler_suspect: typing.Dict[int, float] = {}
        self._started_at: typing.Optional[float] = None
        self._kv_fail_since: typing.Optional[float] = None
        self.event: typing.Optional[str] = None  # human-readable cause
        self.lapsed: typing.List[int] = []

    # -- lease lifecycle ----------------------------------------------------

    def _key(self, pid: int) -> str:
        return f"{LEASE_PREFIX}g{self.gen}/p{pid}"

    def start(self) -> "ElasticAgent":
        self._started_at = self._clock()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="elastic-lease")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s * 2 + 1)

    def membership_event(self) -> typing.Optional[str]:
        """Non-None once a membership change was detected — the train
        loop's between-steps check (the clean exit path; the agent's
        force-exit is the backstop for a wedged main thread)."""
        return self.event

    # -- the heartbeat thread ----------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # never kill the lease on a scan bug
                print(f"WARNING: elastic lease tick failed: {e}", flush=True)
            if self.event is not None:
                self._trigger_exit()
                return
            self._stop.wait(self.interval_s)

    def tick(self) -> typing.Optional[str]:
        """One heartbeat + liveness scan (public for the unit tests)."""
        now = self._clock()
        self._seq += 1
        lease = {"seq": self._seq, "ospid": os.getpid()}
        if self._progress is not None:
            try:
                lease["step"] = int(self._progress())
            except Exception:
                pass
        ok = self._kv_put(self._key(self.process_index), json.dumps(lease))
        # the beat event is the causal ANCHOR: a peer's lease scan that saw
        # seq N happened after this rank recorded beat N — forensics orders
        # cross-process events through exactly these (seq, observer) pairs
        self._recorder.record("beat", rank=self.process_index, beat=self._seq,
                              gen=self.gen, step=lease.get("step"))
        if not ok:
            # the KV store lives on the coordinator (process 0): repeated
            # publish failure = the coordinator itself is gone, which is a
            # membership event for everyone else
            if self._kv_fail_since is None:
                self._kv_fail_since = now
            elif now - self._kv_fail_since > self.timeout_s:
                self._record_event("coordination service unreachable for "
                                   f"{now - self._kv_fail_since:.1f}s "
                                   "(coordinator lost?)", lapsed=[0])
                return self.event
        else:
            self._kv_fail_since = None
        table = dict(self._scan(now))
        if self._last_seen:
            # which peer beat this scan OBSERVED: the forensics timeline's
            # cross-process ordering edges (beat(p, s) happened-before any
            # scan that saw p at seq >= s)
            self._recorder.record(
                "lease_scan", rank=self.process_index, gen=self.gen,
                peers={str(p): s for p, s in self._last_seen.items()},
                ages={str(p): round(a, 3) for p, a in table.items()
                      if a is not None})
        if self.process_index == 0:
            self._mirror(table, now)
            if self.straggler_factor > 0:
                self._check_stragglers(now, table)
        lapsed = [pid for pid, age in table.items()
                  if age is not None and age > self.timeout_s]
        # a peer that NEVER published only counts once the generation had
        # time to come up: processes start the agent at different times
        # (compile skew), so missing keys age against the agent's own start
        started = self._started_at if self._started_at is not None else now
        missing = [pid for pid, age in table.items() if age is None
                   and now - started > self.timeout_s]
        if lapsed or missing:
            self._record_event(
                "peer lease(s) lapsed: "
                + ", ".join(f"p{p}" for p in sorted(lapsed + missing)),
                lapsed=sorted(lapsed + missing))
        return self.event

    def _scan(self, now: float) -> typing.Iterator[
            typing.Tuple[int, typing.Optional[float]]]:
        """(peer, seconds since its lease last ADVANCED | None if never
        seen).  Ages are measured on the LOCAL monotonic clock from the
        moment the beat count changed — no cross-host clock comparison."""
        seen: typing.Dict[int, int] = {}
        for key, value in self._kv_dir_get(f"{LEASE_PREFIX}g{self.gen}/"):
            name = key.rsplit("/", 1)[-1]
            if not name.startswith("p"):
                continue
            try:
                payload = json.loads(value)
                pid_seen = int(name[1:])
                seen[pid_seen] = int(payload["seq"])
                step = payload.get("step")
                if step is not None:
                    self._note_step(pid_seen, int(step), now)
            except (ValueError, KeyError, json.JSONDecodeError):
                # a malformed lease value (torn KV write) must not abort
                # the WHOLE scan — liveness detection keeps running on the
                # peers that parsed
                continue
        self._last_seen = dict(seen)
        if self._progress is not None:
            try:
                self._note_step(self.process_index,
                                int(self._progress()), now)
            except Exception:
                pass
        for pid in range(self.process_count):
            if pid == self.process_index:
                continue
            if pid not in seen:
                yield pid, (None if pid not in self._peer_beats
                            else now - self._peer_beats[pid][1])
                continue
            seq = seen[pid]
            last = self._peer_beats.get(pid)
            if last is None or seq != last[0]:
                self._peer_beats[pid] = (seq, now)
                yield pid, 0.0
            else:
                yield pid, now - last[1]

    # -- straggler detection (docs/OBSERVABILITY.md 'Flight recorder') -------

    def _note_step(self, rank: int, step: int, now: float) -> None:
        last = self._rank_steps.get(rank)
        if last is None or step > last[0]:
            if last is not None and step > last[0]:
                self._step_intervals[rank] = (now - last[1]) \
                    / max(1, step - last[0])
            self._rank_steps[rank] = (step, now)
            self._straggler_flagged.discard(rank)
            self._straggler_suspect.pop(rank, None)

    def _check_stragglers(self, now: float,
                          table: typing.Dict[int, typing.Optional[float]]
                          ) -> None:
        """Flag a slow-but-alive rank BEFORE its lease lapses: its lease
        keeps beating (the agent thread is fine) but its published step
        lags the fleet and has not advanced for straggler_factor x the
        fleet-median per-step interval.  Ranks AT the fleet-max step are
        exempt — a finished (or sync-point-blocked) fast rank plateaus at
        the max and is waiting on the straggler, not the other way
        around."""
        if len(self._rank_steps) < 2 or not self._step_intervals:
            return
        intervals = sorted(self._step_intervals.values())
        median = intervals[len(intervals) // 2]
        threshold = max(self.straggler_factor * median, 2 * self.interval_s)
        max_step = max(s for s, _ in self._rank_steps.values())
        for rank, (step, advanced_at) in sorted(self._rank_steps.items()):
            if step >= max_step or rank in self._straggler_flagged:
                self._straggler_suspect.pop(rank, None)
                continue
            age = now - advanced_at
            lease_age = 0.0 if rank == self.process_index \
                else (table.get(rank) or 0.0)
            # only a rank whose LEASE is alive is a straggler — a lapsed
            # lease is a membership event, handled by the caller
            if not (age > threshold and lease_age <= self.timeout_s):
                self._straggler_suspect.pop(rank, None)
                continue
            # two-scan confirmation: when a fleet-wide stall clears, the
            # fastest rank races ahead while a peer's lease value is up to
            # one publish interval stale — a single-scan rule would flag
            # that healthy peer.  A real straggler stays suspect across
            # scans; the stale lease refreshes within one interval
            first = self._straggler_suspect.setdefault(rank, now)
            if now - first >= self.interval_s:
                self._straggler_flagged.add(rank)
                self._straggler_suspect.pop(rank, None)
                print(f"ELASTIC: straggler suspected p{rank} (step {step} "
                      f"vs fleet max {max_step}; no step advance for "
                      f"{age:.1f}s vs median step {median:.2f}s; lease "
                      "still beating)", flush=True)
                self._recorder.record(
                    "straggler", rank=rank, step=step, fleet_max=max_step,
                    stall_s=round(age, 3), median_step_s=round(median, 4),
                    gen=self.gen)
                if self._on_straggler is not None:
                    try:
                        self._on_straggler(rank, age, median)
                    except Exception:
                        pass

    def _record_event(self, cause: str, lapsed: typing.List[int]) -> None:
        if self.event is not None:
            return
        self.event = cause
        self.lapsed = lapsed
        print(f"ELASTIC: membership change detected (generation "
              f"{self.gen}): {cause}; exiting "
              f"{MEMBERSHIP_EXIT_CODE} for the elastic controller",
              flush=True)
        # the incident record, flushed IMMEDIATELY: even a SIGKILL landing
        # during the exit grace leaves the detection on disk
        self._recorder.record("membership", rank=self.process_index,
                              gen=self.gen, cause=cause, lapsed=lapsed)
        self._recorder.flush(reason="membership")
        try:
            self._write_marker()
        except Exception as e:
            print(f"WARNING: membership marker write failed: {e}",
                  flush=True)
        if self._on_event is not None:
            try:
                self._on_event(cause)
            except Exception:
                pass

    def _trigger_exit(self) -> None:
        """Grace for the main loop's own check, then force-exit: the main
        thread may be wedged in a collective against the dead rank."""
        deadline = self._clock() + self.exit_grace_s
        while self._clock() < deadline:
            if self._stop.is_set():
                return  # the loop noticed and is exiting cleanly
            time.sleep(0.05)
        if self._pre_exit is not None:
            # last-chance host-side accounting (the chief's DataLog flush,
            # the chrome-trace ring dump) before os._exit skips every
            # finally: the callback must be device-free and idempotent
            # against the main thread's own cleanup (train_loop guards it
            # with a once-lock)
            try:
                self._pre_exit()
            except Exception as e:
                print(f"WARNING: elastic pre-exit hook failed: {e}",
                      flush=True)
        # the blackbox MUST survive the force-exit: record the exit and
        # flush here, past the pre_exit hook, so the ring carries the full
        # incident (membership event + this exit) no matter what the hook
        # did — os._exit skips every finally-path flush
        self._recorder.record("exit", rank=self.process_index, gen=self.gen,
                              code=MEMBERSHIP_EXIT_CODE, path="force",
                              cause=self.event)
        self._recorder.flush(reason="force-exit")
        self._exit(MEMBERSHIP_EXIT_CODE)

    # -- shared-storage mirror / marker --------------------------------------

    def _mirror(self, table: typing.Dict[int, typing.Optional[float]],
                now: float) -> None:
        from ..utils import fs
        fs.makedirs(elastic_dir(self.model_path))
        leases = {str(self.process_index): {"age_s": 0.0, "seq": self._seq},
                  **{str(pid): {"age_s": age} for pid, age
                     in table.items() if age is not None}}
        # per-rank step progress (from the lease heartbeats): the operator
        # — and the straggler story — can read fleet progress off shared
        # storage without touching any rank
        for pid, (step, _) in self._rank_steps.items():
            if str(pid) in leases:
                leases[str(pid)]["step"] = step
        payload = {
            "generation": self.gen,
            "world_size": self.process_count,
            "leases": leases,
        }
        with fs.open_(lease_mirror_path(self.model_path), "w") as f:
            json.dump(payload, f)

    def _write_marker(self) -> None:
        from ..utils import fs
        fs.makedirs(elastic_dir(self.model_path))
        with fs.open_(membership_marker_path(self.model_path, self.gen),
                      "w") as f:
            json.dump({"generation": self.gen, "lapsed": self.lapsed,
                       "cause": self.event,
                       "reporter": self.process_index}, f)


# ---- controller side (no jax; scripts/run_manager.py imports lazily) -------

_CKPT_NAME = re.compile(r"^ckpt_(\d+)$")


def latest_complete_step(model_path: str) -> int:
    """Newest COMMITTED checkpoint step under ``model_path`` (-1 none).
    Directory-name scan only — commit is an atomic rename from
    ``ckpt_<step>.tmp``, so a listed ``ckpt_<step>`` is complete (torn
    saves keep the ``.tmp`` suffix and never match).  jax-free through the
    fs seam: the elastic controller polls this to pick grow boundaries."""
    from ..utils import fs
    try:
        names = fs.listdir(model_path)
    except (OSError, FileNotFoundError):
        return -1
    steps = [int(m.group(1)) for m in map(_CKPT_NAME.match, names) if m]
    return max(steps, default=-1)


def read_membership_marker(model_path: str, gen: int) -> typing.Optional[dict]:
    path = os.path.join(model_path, "elastic", f"membership_g{gen}.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def read_preempt_notice(model_path: str) -> typing.Optional[dict]:
    path = os.path.join(model_path, "elastic", "preempt.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def clear_preempt_notice(model_path: str) -> None:
    try:
        os.remove(os.path.join(model_path, "elastic", "preempt.json"))
    except OSError:
        pass


def classify_exit(rc: typing.Optional[int]) -> str:
    """Controller-side exit classification:

    * ``killed``     — SIGKILL'd from outside (capacity loss; 137 is the
                       shell spelling of -9)
    * ``membership`` — a survivor that self-exited on a lapsed peer lease
    * ``collateral`` — jax's own runtime noticed the dead rank first
                       (SIGABRT "another task died" / SIGSEGV teardown)
    * ``preempted``  — graceful 143 (emergency checkpoint written)
    * ``ok`` / ``running`` / ``crash``
    """
    if rc is None:
        return "running"
    if rc == 0:
        return "ok"
    if rc == 143:
        return "preempted"
    if rc == MEMBERSHIP_EXIT_CODE:
        return "membership"
    if rc in (137, -9):
        return "killed"
    if rc in (134, -6, 139, -11, -15):
        # SIGABRT "another task died" / SIGSEGV teardown / a drain-TERM
        # that found the rank wedged in a dead collective (the graceful
        # handler never gets a step boundary to act on, so the default
        # disposition kills it: -15)
        return "collateral"
    return "crash"
