"""jax.distributed bootstrap: coordinator discovery, topology report,
clean teardown (docs/DISTRIBUTED.md).

Two discovery paths, checked in order by ``maybe_initialize``:

1. **Explicit flags** (the CPU multiprocess rig, scripts/run_manager.py
   ``--num-processes`` fan-out): ``HBNLP_COORDINATOR`` (host:port),
   ``HBNLP_NUM_PROCESSES``, ``HBNLP_PROCESS_ID``.  All three must be set;
   a partial set is a configuration error and fails loudly rather than
   silently running single-process.
2. **Standard environment / TPU metadata**: ``JAX_COORDINATOR_ADDRESS``
   (or nothing at all on a Cloud TPU pod slice, where jax's cluster
   detection reads the metadata server).  ``maybe_initialize`` calls the
   no-arg ``jax.distributed.initialize()`` and lets jax autodiscover.

Everything else here is coordination-service plumbing (barriers and a
key-value store over the coordinator's gRPC channel — **no device
collectives**), which makes it safe to call from background threads while
the main thread runs jitted steps: the async checkpoint commit barrier and
the cross-host telemetry merge both depend on that property.
"""
from __future__ import annotations

import os
import time
import typing

#: explicit-flag env vars for the CPU multiprocess rig (docs/DISTRIBUTED.md)
COORDINATOR_ENV = "HBNLP_COORDINATOR"
NUM_PROCESSES_ENV = "HBNLP_NUM_PROCESSES"
PROCESS_ID_ENV = "HBNLP_PROCESS_ID"
#: standard jax env var — set by TPU pod launchers / k8s manifests
JAX_COORDINATOR_ENV = "JAX_COORDINATOR_ADDRESS"

_initialized_here = False


def free_port() -> int:
    """An OS-assigned free localhost port — for launching a coordinator on
    the local rig (run_manager fleet, bench_multihost, tests).  One shared
    helper so a future fix (SO_REUSEADDR, IPv6) lands everywhere at once."""
    import socket
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def is_initialized() -> bool:
    """True when this process is part of an initialized jax.distributed
    cluster (whether this module did the initializing or not)."""
    try:
        from jax._src import distributed
        return distributed.global_state.client is not None
    except Exception:
        return False


def maybe_initialize(verbose: bool = True) -> bool:
    """Initialize ``jax.distributed`` when the environment asks for it;
    return True iff this process is (now) part of a multi-process cluster.

    Single-process runs (no coordinator env at all) return False and touch
    nothing — every call site stays valid on a laptop, the CI rig, and a
    pod with the same code path.
    """
    global _initialized_here
    if is_initialized():
        return True
    import jax
    explicit = os.environ.get(COORDINATOR_ENV)
    if (explicit or os.environ.get(JAX_COORDINATOR_ENV)) and \
            os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # the CPU rig: XLA's default CPU client refuses multi-process
        # computations ("Multiprocess computations aren't implemented on
        # the CPU backend") — gloo-over-TCP collectives make the virtual
        # pod real.  Must be set BEFORE the backend initialises, which is
        # why it lives here and not at a call site.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    if explicit:
        missing = [k for k in (NUM_PROCESSES_ENV, PROCESS_ID_ENV)
                   if not os.environ.get(k)]
        if missing:
            raise RuntimeError(
                f"{COORDINATOR_ENV} is set but {missing} are not: the "
                "explicit-flag rig needs all three (see docs/DISTRIBUTED.md)")
        jax.distributed.initialize(
            coordinator_address=explicit,
            num_processes=int(os.environ[NUM_PROCESSES_ENV]),
            process_id=int(os.environ[PROCESS_ID_ENV]))
        _initialized_here = True
    elif os.environ.get(JAX_COORDINATOR_ENV):
        # standard env: jax reads JAX_COORDINATOR_ADDRESS + cluster metadata
        # (TPU pod slices fill in num_processes/process_id from the metadata
        # server; GKE sets the full set)
        jax.distributed.initialize()
        _initialized_here = True
    else:
        return False
    if verbose:
        print(format_topology(topology_report()), flush=True)
    return True


def topology_report() -> dict:
    """Where this process sits in the cluster: process index/count, local
    devices (with TPU slice indices when the platform reports them), global
    device count, backend.  Safe single-process (reports a 1-process
    topology)."""
    import jax
    local = []
    for d in jax.local_devices():
        entry = {"id": int(d.id), "kind": getattr(d, "device_kind", "?")}
        # TPU v4+ multi-slice: which slice this chip belongs to
        slice_idx = getattr(d, "slice_index", None)
        if slice_idx is not None:
            entry["slice"] = int(slice_idx)
        local.append(entry)
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "backend": jax.default_backend(),
        "local_devices": local,
        "global_device_count": len(jax.devices()),
        "coordinator": os.environ.get(COORDINATOR_ENV)
        or os.environ.get(JAX_COORDINATOR_ENV) or "",
    }


def format_topology(report: dict) -> str:
    slices = sorted({d.get("slice") for d in report["local_devices"]
                     if d.get("slice") is not None})
    slice_note = f" slice(s) {slices}" if slices else ""
    return (f"distributed: process {report['process_index']}/"
            f"{report['process_count']} backend={report['backend']} "
            f"local_devices={len(report['local_devices'])} "
            f"global_devices={report['global_device_count']}{slice_note}")


def coordination_client():
    """The jax coordination-service client, or None single-process.  Its
    barriers and KV ops ride the coordinator's gRPC channel — no device
    collectives — so they are safe from any thread at any time."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:
        return None


def barrier(name: str, timeout_s: float = 600.0) -> None:
    """Block until every process reaches ``barrier(name)``; no-op
    single-process.  Raises ``TimeoutError`` naming the barrier on
    timeout/peer-death — a peer that died mid-protocol surfaces as a
    NAMED error at the caller (which protocol step, how long) instead of
    hanging forever or raising an anonymous gRPC status
    (tests/distributed_test.py::kv_barrier_edge_cases_test)."""
    client = coordination_client()
    if client is None:
        return
    from ..telemetry import events as _flight
    t0 = time.monotonic()
    try:
        client.wait_at_barrier(name, int(timeout_s * 1000))
        # collective-phase marker (docs/OBSERVABILITY.md 'Flight
        # recorder'): barriers are the pod's ordering points — the
        # forensic timeline shows which protocol step each rank reached
        _flight.record("collective", phase=name, status="ok",
                       seconds=round(time.monotonic() - t0, 3))
    except Exception as e:
        # one error type for every barrier failure (callers handle
        # timeout and peer-death identically: the pod is broken), but the
        # message reports the MEASURED wait — an instant gRPC failure
        # (dead coordinator, bad barrier id) must not masquerade as a
        # full timeout_s wait on a wedged peer
        elapsed = time.monotonic() - t0
        _flight.record("collective", phase=name, status="failed",
                       seconds=round(elapsed, 3), error=str(e))
        _flight.flush(reason="barrier-failure")
        raise TimeoutError(
            f"coordination barrier {name!r} failed after {elapsed:.1f}s "
            f"(timeout {timeout_s}s; peer dead or wedged "
            f"mid-protocol?): {e}") from e


def kv_put(key: str, value: str) -> bool:
    """Publish ``value`` under ``key`` in the coordination KV store
    (overwriting any earlier value); False single-process / on error."""
    client = coordination_client()
    if client is None:
        return False
    try:
        client.key_value_set(key, value, allow_overwrite=True)
        return True
    except TypeError:
        # older binding without allow_overwrite: delete-then-set
        try:
            try:
                client.key_value_delete(key)
            except Exception:
                pass
            client.key_value_set(key, value)
            return True
        except Exception:
            return False
    except Exception:
        return False


def kv_dir_get(prefix: str) -> typing.List[typing.Tuple[str, str]]:
    """All (key, value) pairs under ``prefix``; [] single-process or when
    nothing was published."""
    client = coordination_client()
    if client is None:
        return []
    try:
        return list(client.key_value_dir_get(prefix))
    except Exception:
        return []


def shutdown() -> None:
    """Tear down jax.distributed if THIS module initialized it (idempotent,
    never raises).  Called on the preemption/exit path so the coordinator
    sees a clean disconnect instead of a gRPC reset — peers then fail their
    next barrier with a named error rather than a hang."""
    global _initialized_here
    if not _initialized_here:
        return
    _initialized_here = False
    try:
        import jax
        jax.distributed.shutdown()
    except Exception as e:
        print(f"WARNING: jax.distributed.shutdown failed: {e}", flush=True)
