"""Async sharded checkpointing: double-buffered background saver with a
step-tagged commit barrier (docs/DISTRIBUTED.md 'Async checkpoints').

The synchronous save (train/checkpoint.py) holds the step thread through
device→host staging AND serialization AND every fs write AND the pod-wide
barriers — minutes for GB-scale state on gs://, all of it training stall.
``AsyncCheckpointer`` splits the save at the only boundary donation allows:

* **Staging stays on the submitting thread.**  The train step DONATES its
  state buffers, so the next ``trainer.step`` call invalidates every device
  array a background thread might still be reading — a concurrent
  ``device_get`` is a use-after-free race, not an optimization.  ``submit``
  therefore snapshots the state to host before returning: one
  ``copy_to_host_async`` sweep primes every transfer, then one batched
  ``device_get`` drains them (transfers overlap each other instead of
  serializing per ~1GB chunk the way the sync path interleaves
  fetch-then-write).  Cost to the step thread: the D2H copy, nothing else.
* **Everything after the host copy runs on the saver thread**: tobytes,
  checksums, shard files, manifests, the commit barrier, the directory
  rename, pruning.  On remote storage this is the dominant 95%+ of save
  wall time.

Double buffering: at most one save is being written while one more may sit
staged in the queue; a third ``submit`` blocks until the oldest commits, so
host RAM holds at most two extra state copies no matter how hot the
checkpoint cadence is.

The commit barrier is **step-tagged and runs on the coordination service**
(distributed/bootstrap.py ``barrier`` — gRPC to the coordinator, no device
collectives), so the saver thread can rendezvous with its peers while the
main threads are mid-collective in the next train step.  Barrier tags
include a per-process submission sequence number: every process submits
saves in the same order (the checkpoint cadence is step-driven and the
emergency save goes through the pod-wide stop agreement), so sequence
numbers agree and a re-save of the same step (cadence save then emergency
save at step N) cannot collide with its predecessor's barrier ids.

Failure semantics: an exception in the background save (storage outage,
barrier timeout because a peer died mid-protocol) is held and re-raised at
the next ``submit``/``flush`` — the same call sites where the synchronous
save would have raised.  A save that dies between shard write and manifest
commit leaves only a ``.tmp`` directory: ``restore_latest_valid`` never
sees it, so a restart resumes from the previous committed checkpoint
(fault-injected in tests/distributed_test.py).
"""
from __future__ import annotations

import queue
import threading
import time
import typing

import numpy as np

from ..train import checkpoint as ckpt
from ..utils import fs
from . import bootstrap


class _Staged(typing.NamedTuple):
    """Host-side snapshot of one save: everything the writer thread needs,
    with zero references to device arrays."""
    step: int
    nproc: int
    pid: int
    #: full arrays this process writes: [(leaf_index, key, host_array)]
    full: typing.List[typing.Tuple[int, str, np.ndarray]]
    #: owned shards: [(leaf_index, key, shard_index, slice_spec,
    #:                 global_shape, dtype_name, host_array)]
    shards: typing.List[tuple]
    extra: dict


def stage(step: int, variables: dict, opt_state: dict,
          extra: typing.Optional[dict] = None) -> _Staged:
    """Snapshot the state tree to host memory (the only part of a save that
    must happen before the next step donates the buffers).  Single process:
    every leaf.  Multi-host: this process's owned shards (replica 0 of each
    addressable shard) plus, on the chief, every non-distributed array —
    the same writer-role split as the synchronous distributed save."""
    import jax
    tree = {"variables": variables, "opt_state": opt_state}
    leaves = list(ckpt._leaf_files(tree))
    nproc = jax.process_count()
    pid = jax.process_index()
    chief_fetch: typing.List[tuple] = []
    shard_meta: typing.List[tuple] = []
    shard_refs: typing.List[typing.Any] = []
    if nproc > 1:
        for i, (key, value) in enumerate(leaves):
            if ckpt._is_distributed(value):
                for j, shard in enumerate(value.addressable_shards):
                    if shard.replica_id != 0:
                        continue  # a replicated copy some process owns
                    shard_meta.append(
                        (i, key, j, ckpt._slice_spec(shard.index, value.shape),
                         list(value.shape), ckpt._dtype_name(value.dtype)))
                    shard_refs.append(shard.data)
            elif pid == 0:
                chief_fetch.append((i, key, value))
    else:
        chief_fetch = [(i, key, v) for i, (key, v) in enumerate(leaves)]
    # prime every D2H transfer, then drain: the copies overlap in flight
    # instead of paying a serialized round trip per fetch
    for ref in shard_refs:
        _prime(ref)
    for _, _, ref in chief_fetch:
        _prime(ref)
    fetched_shards = jax.device_get(shard_refs)
    fetched_full = jax.device_get([v for _, _, v in chief_fetch])
    return _Staged(
        step=int(step), nproc=nproc, pid=pid,
        full=[(i, key, np.asarray(h))
              for (i, key, _), h in zip(chief_fetch, fetched_full)],
        shards=[(*meta, np.asarray(h))
                for meta, h in zip(shard_meta, fetched_shards)],
        extra=dict(extra or {}))


def _prime(value) -> None:
    try:
        value.copy_to_host_async()
    except Exception:
        pass  # numpy leaf / backend without async copies: device_get works


def write_staged(model_path: str, staged: _Staged, max_keep: int,
                 barrier_tag: str, barrier_timeout_s: float) -> str:
    """The fs half of a save: serialize ``staged`` into ``ckpt_<step>``.
    Runs entirely on host state — safe on any thread.  Multi-host commits
    through three step-tagged coordination barriers (clear → save → done),
    mirroring the synchronous save's sync_global_devices protocol without
    touching the device path."""
    step = staged.step
    ckpt_dir = fs.join(model_path, f"ckpt_{step}")
    tmp_dir = ckpt_dir + ".tmp"
    if staged.nproc <= 1:
        if ckpt._fsop(fs.exists, tmp_dir):
            ckpt._fsop(fs.rmtree, tmp_dir)
        ckpt._fsop(fs.makedirs, tmp_dir)
        manifest = {"step": step, "process_index": 0, "arrays": {},
                    "extra": staged.extra}
        for i, key, host in staged.full:
            manifest["arrays"][key] = ckpt._write_array_file(
                tmp_dir, f"arr_{i:06d}.bin", host)
        ckpt._write_json(fs.join(tmp_dir, "index.json"), manifest)
        if ckpt._fsop(fs.exists, ckpt_dir):
            ckpt._fsop(fs.rmtree, ckpt_dir)
        # not retried (see the sync save: replace re-runs are not idempotent)
        fs.replace(tmp_dir, ckpt_dir)
        ckpt._prune(model_path, step, max_keep)
        _record_commit(step)
        return ckpt_dir
    pid = staged.pid
    if pid == 0 and ckpt._fsop(fs.exists, tmp_dir):
        ckpt._fsop(fs.rmtree, tmp_dir)
    bootstrap.barrier(f"{barrier_tag}_clear", barrier_timeout_s)
    ckpt._fsop(fs.makedirs, tmp_dir)
    shard_entries = []
    for i, key, j, index, global_shape, dtype, host in staged.shards:
        meta = ckpt._write_array_file(
            tmp_dir, f"arr_{i:06d}_p{pid}_s{j}.bin", host)
        meta.pop("shape")
        shard_entries.append({"key": key, "index": index,
                              "global_shape": global_shape, **meta})
    chief_arrays = {}
    for i, key, host in staged.full:
        chief_arrays[key] = ckpt._write_array_file(
            tmp_dir, f"arr_{i:06d}.bin", host)
    ckpt._write_json(fs.join(tmp_dir, f"shards_{pid}.json"),
                     {"process_index": pid, "shards": shard_entries})
    if pid == 0:
        ckpt._write_json(fs.join(tmp_dir, "index.json"),
                         {"step": step, "distributed": True,
                          "process_count": staged.nproc,
                          "arrays": chief_arrays, "extra": staged.extra})
    # every process's shards + manifests must be durable before the rename
    # makes the checkpoint visible — a peer that died above never reaches
    # this barrier and the commit fails loudly on timeout instead of
    # publishing a checkpoint missing that peer's shards
    bootstrap.barrier(f"{barrier_tag}_save", barrier_timeout_s)
    if pid == 0:
        if ckpt._fsop(fs.exists, ckpt_dir):
            ckpt._fsop(fs.rmtree, ckpt_dir)
        fs.replace(tmp_dir, ckpt_dir)
        ckpt._prune(model_path, step, max_keep)
    bootstrap.barrier(f"{barrier_tag}_done", barrier_timeout_s)
    _record_commit(step)
    return ckpt_dir


def _record_commit(step: int) -> None:
    """Flight-recorder marker at the ACTUAL commit (the saver thread's done
    barrier) — the synchronous path records in ``ckpt.save``; this is the
    async twin, so both timelines carry the recovery point."""
    from ..telemetry import events as _flight
    _flight.record("checkpoint_commit", step=int(step), asynchronous=True)
    _flight.maybe_flush()


class AsyncSaveError(RuntimeError):
    """A background save failed; carries the step that was lost."""

    def __init__(self, step: int, cause: BaseException):
        super().__init__(f"async checkpoint save of step {step} failed: "
                         f"{type(cause).__name__}: {cause}")
        self.step = step
        self.cause = cause


class AsyncCheckpointer:
    """Background checkpoint saver; one instance per training run.

    ``submit`` stages on the calling thread and returns; the write/commit
    runs on a daemon thread.  ``flush`` blocks until every submitted save
    has committed (the emergency-save path calls it before exiting 143 so a
    preemption cannot race a half-committed distributed checkpoint).
    """

    def __init__(self, barrier_timeout_s: float = 600.0):
        self._timeout = float(barrier_timeout_s)
        # maxsize 1 = double buffering: one save being written, at most one
        # more staged and waiting; a third submit blocks on put()
        self._queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._thread: typing.Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._error: typing.Optional[AsyncSaveError] = None
        self._inflight = 0
        self._idle = threading.Condition(self._lock)
        self._seq = 0
        self._closed = False

    # -- public API ----------------------------------------------------------

    def submit(self, model_path: str, step: int, variables: dict,
               opt_state: dict, max_keep: int = 1,
               extra: typing.Optional[dict] = None) -> str:
        """Stage ``step``'s state to host and hand it to the saver thread.
        Raises any error from a PREVIOUS background save (same contract as
        the synchronous ``save`` raising at its call site)."""
        self._raise_pending()
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        t0 = time.monotonic()
        staged = stage(step, variables, opt_state, extra)
        ckpt._metrics()[1].labels(op="stage").observe(time.monotonic() - t0)
        self._ensure_thread()
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._inflight += 1
        try:
            self._queue.put((model_path, staged, max_keep, seq))
        except BaseException:
            with self._lock:
                self._inflight -= 1
            raise
        return fs.join(model_path, f"ckpt_{int(step)}")

    def flush(self, timeout: typing.Optional[float] = None) -> None:
        """Block until every submitted save has committed; re-raise the
        first background failure.  ``timeout`` bounds the wait PER SAVE
        (None = barrier timeout + slack): each completed save resets the
        clock, so two slow-but-healthy in-flight saves get two budgets —
        only a save making no progress for a full budget times out."""
        per_save = timeout if timeout is not None else self._timeout + 60.0
        deadline = time.monotonic() + per_save
        with self._idle:
            last_inflight = self._inflight
            while self._inflight > 0:
                if self._inflight < last_inflight:
                    # progress: a save committed — restart the budget for
                    # the next one instead of abandoning it mid-write
                    last_inflight = self._inflight
                    deadline = time.monotonic() + per_save
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"async checkpoint flush timed out with "
                        f"{self._inflight} save(s) still in flight")
                self._idle.wait(timeout=min(remaining, 1.0))
        self._raise_pending()

    def take_error(self) -> typing.Optional["AsyncSaveError"]:
        """Return-and-clear the held background failure without raising —
        the emergency-save path uses this so an OLD cadence-save failure
        cannot abort the NEW preemption checkpoint (it is logged and the
        emergency save still runs)."""
        with self._lock:
            err, self._error = self._error, None
        return err

    def close(self, timeout: typing.Optional[float] = None) -> None:
        """flush + stop accepting work (idempotent; the daemon thread dies
        with the process)."""
        if self._closed:
            return
        try:
            self.flush(timeout=timeout)
        finally:
            self._closed = True

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._inflight

    # -- internals -----------------------------------------------------------

    def _raise_pending(self) -> None:
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="hbnlp-async-ckpt", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while True:
            model_path, staged, max_keep, seq = self._queue.get()
            t0 = time.monotonic()
            try:
                write_staged(model_path, staged, max_keep,
                             barrier_tag=f"hbnlp_ckpt_{seq}_{staged.step}",
                             barrier_timeout_s=self._timeout)
                ckpt._metrics()[1].labels(op="save").observe(
                    time.monotonic() - t0)
            except BaseException as e:  # held for the next submit/flush
                with self._lock:
                    if self._error is None:
                        self._error = AsyncSaveError(staged.step, e)
            finally:
                with self._idle:
                    self._inflight -= 1
                    self._idle.notify_all()
