"""LR schedule DSL (reference: /root/reference/src/optimizer/learning_rate.py).

``learning_rate_config`` is a dict of named modules applied in order:
linear_warmup, exponential_decay, linear_decay, lower_bound, upper_bound,
each a LearningRateConfig(start_step, final_step, factor).  The reference
computes this host-side in TF and imports it replicated; here it is a pure
jnp function of the global step, traced into the train step.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..config import LearningRateConfig, ModelParameter


def _linear_warmup(lr, step, cfg: LearningRateConfig):
    warmup = jnp.float32(cfg.final_step)
    is_warmup = (step < warmup).astype(jnp.float32)
    factor = is_warmup * (step / warmup) + (1 - is_warmup)
    return lr * factor


def _exponential_decay(lr, step, cfg: LearningRateConfig):
    exp = jnp.maximum(step - jnp.float32(cfg.start_step), 0.)
    return lr * jnp.float32(cfg.factor) ** exp


def _linear_decay(lr, step, cfg: LearningRateConfig):
    start = jnp.float32(cfg.start_step)
    final = jnp.float32(cfg.final_step) - start
    decay = 1 - (step - start) / final
    return lr * jnp.clip(decay, 0., 1.)


def _lower_bound(lr, step, cfg: LearningRateConfig):
    return jnp.maximum(lr, jnp.float32(cfg.factor))


def _upper_bound(lr, step, cfg: LearningRateConfig):
    return jnp.minimum(lr, jnp.float32(cfg.factor))


MODULES = {"linear_warmup": _linear_warmup,
           "exponential_decay": _exponential_decay,
           "linear_decay": _linear_decay,
           "lower_bound": _lower_bound,
           "upper_bound": _upper_bound}


def get_learning_rate(params: ModelParameter, global_step) -> jnp.ndarray:
    """f32 scalar learning rate at ``global_step``."""
    step = jnp.asarray(global_step, jnp.float32)
    lr = jnp.float32(params.learning_rate)
    for name, cfg in params.learning_rate_config.items():
        lr = MODULES[name](lr, step, cfg)
    return lr
