"""Optimizer driver: the '-'/'-:' chain interpreter + update rule.

Reference: /root/reference/src/optimizer/__init__.py.  The reference
re-implements reverse-mode autodiff over the mtf graph (:143-174); here
gradients come from ``jax.grad`` and this module only performs the per-variable
update chain:

  for each var:  g -> chain members -> rezero LR multiplier -> selective
  weight decay (name/shape heuristics, :49-61) -> var -= g

State lives in a per-variable slot dict (optimizer_slice_dtype).  All of it is
a pure (params, grads, state, step) -> (params, state) function, jit/pjit
friendly, with the variable loop unrolled at trace time (XLA fuses the small
per-var element-wise chains).
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelParameter
from ..core.dims import Dim
from .learning_rate import get_learning_rate
from .optimizers import OPTIMIZERS, VarCtx, jax_rsqrt

Params = typing.Dict[str, jax.Array]
OptState = typing.Dict[str, typing.Dict[str, jax.Array]]


def _feature_dims_used(params: ModelParameter, dims: typing.Tuple[Dim, ...]) -> bool:
    names = [d.name.lstrip("_") for d in dims]
    return sum(f.name in names for f in params.feature_dims) >= 2


def is_large_tensor(params: ModelParameter, name: str,
                    dims: typing.Tuple[Dim, ...], size: int) -> bool:
    """Weight-decay eligibility heuristics (reference :49-61)."""
    features_used = _feature_dims_used(params, dims)
    large = features_used and len(dims) > len(params.feature_dims)
    large |= (not features_used) and len(dims) >= 2
    large &= size > 1
    large &= "norm" not in name
    large &= "rezero" not in name
    large &= "embed" not in name
    large &= "input" not in name or "lang_in" in name or "vid_in" in name
    large &= "output" not in name or "lang_out" in name or "vid_out" in name
    return bool(large)


def parse_chain(optimizer: str) -> typing.List[typing.Tuple[str, typing.Tuple[str, ...]]]:
    chain = []
    for member in optimizer.split("-"):
        name, *args = member.split(":")
        if name not in OPTIMIZERS:
            raise ValueError(f"unknown optimizer chain member {name!r}")
        chain.append((name, tuple(args)))
    return chain


def _zeros_for(variable, shape, dtype):
    """Zero slot laid out like its variable: same-shape slots inherit the
    variable's sharding, reduced-shape slots (SM3 per-dim buckets, scalars)
    replicate over the same mesh.  A bare ``jnp.zeros`` would commit to the
    process-local default device — mixed with globally-sharded variables in
    one jit, a multi-controller run rejects that ('incompatible devices')."""
    if isinstance(variable, jax.Array) and isinstance(
            variable.sharding, jax.sharding.NamedSharding):
        mesh = variable.sharding.mesh
        sharding = variable.sharding if tuple(shape) == tuple(variable.shape) \
            else jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
        host = np.zeros(shape, dtype)
        return jax.make_array_from_callback(tuple(shape), sharding,
                                            lambda idx: host[idx])
    return jnp.zeros(shape, dtype)


class Optimizer:
    def __init__(self, params: ModelParameter,
                 param_dims: typing.Dict[str, tuple]):
        self.params = params
        self.param_dims = param_dims
        self.chain = parse_chain(params.optimizer)
        self._needs_global_norm = any(n == "global_l2norm_clip" for n, _ in self.chain)

    def init(self, variables: Params) -> OptState:
        """Zero-initialised slots, discovered by abstractly tracing the chain."""
        state: OptState = {}
        opt_dtype = self.params.optimizer_slice_dtype
        calc = self.params.optimizer_calculation_dtype
        for name, value in variables.items():
            def _shapes(shape=value.shape):
                ctx = VarCtx(name=name,
                             grad=jnp.zeros(shape, calc),
                             value=jnp.zeros(shape, calc),
                             slots={}, new_slots={},
                             learning_rate=jnp.float32(0),
                             beta1=jnp.float32(self.params.opt_beta1),
                             beta2=jnp.float32(self.params.opt_beta2),
                             step_count=jnp.float32(1),
                             global_norm_reciprocal=jnp.float32(1)
                             if self._needs_global_norm else None,
                             slot_dtype=opt_dtype)
                for opt_name, args in self.chain:
                    ctx.grad = OPTIMIZERS[opt_name](ctx, *args)
                return ctx.new_slots
            slots = jax.eval_shape(_shapes)
            state[name] = {k: _zeros_for(value, v.shape, opt_dtype)
                           for k, v in slots.items()}
        return state

    def update(self, variables: Params, grads: Params, state: OptState,
               global_step: jax.Array) -> typing.Tuple[Params, OptState, jax.Array]:
        """One optimizer step; returns (new_vars, new_state, learning_rate)."""
        p = self.params
        calc = p.optimizer_calculation_dtype
        lr = get_learning_rate(p, global_step).astype(calc)
        # reference step bookkeeping (:89-96): with grad_accumulation==1 the
        # debias exponent is global_step + 1
        step_count = jnp.asarray(global_step, calc) + 1
        beta1 = jnp.asarray(p.opt_beta1, calc)
        beta2 = jnp.asarray(p.opt_beta2, calc)

        global_norm_recip = None
        if self._needs_global_norm:
            clip = next(float(a[0]) for n, a in self.chain if n == "global_l2norm_clip")
            total = sum(jnp.sum(jnp.square(g.astype(calc))) for g in grads.values())
            global_norm_recip = jax_rsqrt(jnp.maximum(total, clip ** -2))

        new_vars: Params = {}
        new_state: OptState = {}
        for name, value in variables.items():
            grad = grads[name].astype(calc)
            ctx = VarCtx(name=name, grad=grad, value=value.astype(calc),
                         slots=state.get(name, {}), new_slots={},
                         learning_rate=lr, beta1=beta1, beta2=beta2,
                         step_count=step_count,
                         global_norm_reciprocal=global_norm_recip,
                         slot_dtype=p.optimizer_slice_dtype)
            for opt_name, args in self.chain:
                ctx.grad = OPTIMIZERS[opt_name](ctx, *args)

            if "rezero" in name:
                ctx.grad = ctx.grad * p.rezero_lr_multiplier

            dims = self.param_dims.get(name, ())
            if p.weight_decay > 0 and is_large_tensor(p, name, dims, value.size):
                ctx.grad = ctx.grad + ctx.value * lr * p.weight_decay

            new_vars[name] = (value.astype(calc) - ctx.grad).astype(value.dtype)
            new_state[name] = ctx.new_slots
        return new_vars, new_state, lr
