"""Optimizer-chain members (reference: /root/reference/src/optimizer/optimizers.py).

The config string ``optimizer`` is a '-'-chain with ':'-args, e.g.
``"adaptive_clip:0.003-sm3-momentum:0.9:1:1-learning_rate"``, folded left over
the gradient.  Each member is a pure function (ctx, *args) -> transformed
gradient; stateful members read/write named slots in ``ctx.slots`` (the
jax-native replacement for the reference's per-variable slot variables named
``{var}/{optimizer}/{slot}``, src/optimizer/backend.py:23-25).
"""
from __future__ import annotations

import dataclasses
import typing

import jax.numpy as jnp

Array = jnp.ndarray


@dataclasses.dataclass
class VarCtx:
    """Per-variable context flowing through the chain
    (reference: src/optimizer/context.py)."""
    name: str
    grad: Array                      # in optimizer_calculation_dtype
    value: Array                     # current weight, optimizer_calculation_dtype
    slots: typing.Dict[str, Array]   # state in (read: prev, write: new)
    new_slots: typing.Dict[str, Array]
    learning_rate: Array
    beta1: Array
    beta2: Array
    step_count: Array                # global_step + 1 (debias exponent)
    global_norm_reciprocal: typing.Optional[Array] = None
    slot_dtype: typing.Any = jnp.float32

    def get_slot(self, opt: str, slot: str, shape) -> Array:
        key = f"{opt}/{slot}"
        if key in self.slots:
            return self.slots[key].astype(self.grad.dtype)
        return jnp.zeros(shape, self.grad.dtype)

    def set_slot(self, opt: str, slot: str, value: Array):
        self.new_slots[f"{opt}/{slot}"] = value.astype(self.slot_dtype)


def _opt_rsqrt(x: Array) -> Array:
    return 1.0 / jnp.maximum(jnp.sqrt(x), 1e-5)


def _debias_momentum(ctx: VarCtx, momentum: Array) -> Array:
    return 1.0 / (1.0 - momentum ** ctx.step_count)


def adam(ctx: VarCtx) -> Array:
    p2 = ctx.get_slot("adam", "exp_avg_p2", ctx.grad.shape)
    p1 = ctx.get_slot("adam", "exp_avg_p1", ctx.grad.shape)
    p2 = p2 * ctx.beta2 + jnp.square(ctx.grad) * (1 - ctx.beta2)
    p1 = p1 * ctx.beta1 + ctx.grad * (1 - ctx.beta1)
    ctx.set_slot("adam", "exp_avg_p2", p2)
    ctx.set_slot("adam", "exp_avg_p1", p1)
    return _opt_rsqrt(p2 * _debias_momentum(ctx, ctx.beta2)) * p1 \
        * _debias_momentum(ctx, ctx.beta1)


def novograd(ctx: VarCtx) -> Array:
    if ctx.grad.ndim == 0:
        return adam(ctx)
    p1 = ctx.get_slot("novograd", "exp_avg_p1", ctx.grad.shape)
    p2 = ctx.get_slot("novograd", "exp_avg_p2", ())
    p1 = ctx.beta1 * p1 + ctx.grad * _opt_rsqrt(p2)
    p2 = p2 * ctx.beta2 + jnp.sum(jnp.square(ctx.grad)) * (1 - ctx.beta2)
    ctx.set_slot("novograd", "exp_avg_p1", p1)
    ctx.set_slot("novograd", "exp_avg_p2", p2)
    return ctx.beta1 * p1 + ctx.grad * _opt_rsqrt(p2 * _debias_momentum(ctx, ctx.beta2))


def sm3(ctx: VarCtx) -> Array:
    """SM3 with per-dim min-bucket accumulators (optimizers.py:60-76)."""
    if ctx.grad.ndim == 0:
        return adam(ctx)
    shape = ctx.grad.shape
    bufs = []
    acc = None
    for i in range(ctx.grad.ndim):
        view = [1] * ctx.grad.ndim
        view[i] = shape[i]
        buf = ctx.get_slot("sm3", f"dim{i}", (shape[i],)).reshape(view)
        bufs.append(buf)
        acc = buf if acc is None else jnp.minimum(acc, buf)
    acc = acc + jnp.square(ctx.grad)
    for i in range(ctx.grad.ndim):
        axes = tuple(a for a in range(ctx.grad.ndim) if a != i)
        ctx.set_slot("sm3", f"dim{i}", jnp.max(acc, axis=axes))
    return ctx.grad * _opt_rsqrt(acc)


def adaptive_clip(ctx: VarCtx, gradient_clip: str) -> Array:
    """AGC (optimizers.py:79-84): g * min(||w|| * clip / ||g||, 1)."""
    clip = float(gradient_clip)
    grd_norm_recip = jnp.minimum(jax_rsqrt(jnp.sum(jnp.square(ctx.grad))), 1e6)
    wgt_norm = jnp.maximum(jnp.sqrt(jnp.sum(jnp.square(ctx.value))), 1e-3)
    return ctx.grad * jnp.minimum(wgt_norm * grd_norm_recip * clip, 1)


def jax_rsqrt(x: Array) -> Array:
    import jax.lax
    return jax.lax.rsqrt(x)


def l2norm_clip(ctx: VarCtx, gradient_clip: str) -> Array:
    clip = float(gradient_clip)
    return ctx.grad * clip * jax_rsqrt(jnp.maximum(jnp.sum(jnp.square(ctx.grad)),
                                                   clip ** -2))


def global_l2norm_clip(ctx: VarCtx, gradient_clip: str) -> Array:
    clip = float(gradient_clip)
    assert ctx.global_norm_reciprocal is not None, \
        "chain driver must precompute the global norm"
    return ctx.grad * clip * ctx.global_norm_reciprocal


def value_clip(ctx: VarCtx, gradient_clip: str) -> Array:
    clip = float(gradient_clip)
    return jnp.clip(ctx.grad, -clip, clip)


def gradient_centralisation(ctx: VarCtx) -> Array:
    return ctx.grad - jnp.mean(ctx.grad)


def weight_centralisation(ctx: VarCtx) -> Array:
    return ctx.grad + jnp.mean(ctx.value)


def multiply_learning_rate(ctx: VarCtx) -> Array:
    return ctx.grad * ctx.learning_rate


def momentum(ctx: VarCtx, momentum_multiplier: str, gradient_multiplier: str,
             nesterov: str) -> Array:
    nesterov_b = bool(int(nesterov))
    mm = float(momentum_multiplier)
    gm = float(gradient_multiplier)
    state = ctx.get_slot("momentum", "momentum", ctx.grad.shape)
    new_state = mm * state + ctx.grad * gm
    ctx.set_slot("momentum", "momentum", new_state)
    if not nesterov_b:
        return new_state
    return ctx.grad + mm * new_state


OPTIMIZERS: typing.Dict[str, typing.Callable] = {
    "adam": adam,
    "sm3": sm3,
    "novograd": novograd,
    "adaptive_clip": adaptive_clip,
    "l2norm_clip": l2norm_clip,
    "value_clip": value_clip,
    "gradient_centralisation": gradient_centralisation,
    "weight_centralisation": weight_centralisation,
    "learning_rate": multiply_learning_rate,
    "global_l2norm_clip": global_l2norm_clip,
    "momentum": momentum,
}


def graft(ctx: VarCtx, optimizer: str, *args: str) -> Array:
    """Norm-grafting: direction of g, magnitude of the grafted optimizer
    (optimizers.py:145-151)."""
    other = OPTIMIZERS[optimizer](ctx, *args)
    return (ctx.grad * jax_rsqrt(jnp.sum(jnp.square(ctx.grad)))
            * jnp.sqrt(jnp.sum(jnp.square(other))))


OPTIMIZERS["graft"] = graft
