"""Multi-loss gradient surgery: pcgrad and mgda.

Reference: /root/reference/src/optimizer/gradients.py (hooked into the manual
backward sweep) and the MGDA gamma solve in src/optimizer/__init__.py:110-126.
Here per-loss gradients come from separate ``jax.grad`` calls and are combined
functionally.  Both strategies only touch 'body' variables, like the
reference.
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp

Params = typing.Dict[str, jax.Array]


def _is_body(name: str) -> bool:
    return "body" in name


def pcgrad_combine(grads_per_loss: typing.List[Params]) -> Params:
    """Project conflicting gradients (PCGrad) for body vars; linear sum
    elsewhere (gradients.py:11-35)."""
    out: Params = {}
    for name in grads_per_loss[0]:
        gs = [g[name] for g in grads_per_loss]
        if not _is_body(name) or len(gs) == 1:
            out[name] = sum(gs[1:], gs[0])
            continue
        all_grads = list(gs)
        g_square = [1e-8 + jnp.sum(g * g) for g in all_grads[1:]]
        for _ in range(len(all_grads)):
            grad = all_grads.pop(0)
            for g, sq in zip(all_grads, g_square):
                grad = grad - g * jnp.minimum(jnp.sum(grad * g), 0) * sq
            all_grads.append(grad)
            g_square.append(jnp.sum(g * g))
        out[name] = sum(all_grads[1:], all_grads[0])
    return out


def mgda_gamma(grads_per_loss: typing.List[Params]) -> jax.Array:
    """Min-norm two-loss gamma (reference __init__.py:110-126)."""
    assert len(grads_per_loss) >= 2
    v1v1 = v1v2 = v2v2 = 0.
    for name in grads_per_loss[0]:
        if not _is_body(name):
            continue
        g1 = grads_per_loss[0][name].astype(jnp.float32)
        g2 = grads_per_loss[1][name].astype(jnp.float32)
        v1v1 = v1v1 + jnp.sum(g1 * g1)
        v1v2 = v1v2 + jnp.sum(g1 * g2)
        v2v2 = v2v2 + jnp.sum(g2 * g2)
    min_gamma = 0.001
    gamma = (1 - min_gamma) * (v1v2 >= v1v1).astype(jnp.float32)
    gamma = gamma + min_gamma * (v1v2 >= v2v2).astype(jnp.float32) * (gamma == 0)
    gamma = gamma + (-1.) * (gamma == 0) * (v1v2 - v2v2) / (v1v1 + v2v2 - 2 * v1v2)
    return gamma


def mgda_combine(grads_per_loss: typing.List[Params]) -> Params:
    gamma = mgda_gamma(grads_per_loss)
    out: Params = {}
    for name in grads_per_loss[0]:
        g1 = grads_per_loss[0][name]
        g2 = grads_per_loss[1][name]
        out[name] = (g1.astype(jnp.float32) * gamma
                     + g2.astype(jnp.float32) * (1 - gamma)).astype(g1.dtype)
    return out


MULTI_LOSS_GRADIENTS = {"pcgrad": pcgrad_combine, "mgda": mgda_combine}
