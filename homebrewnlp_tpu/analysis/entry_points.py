"""Lowering registry: every jitted entry point the HLO passes audit.

``infer/hlo_check.py`` audited ONE entry point (the decode chunk step);
the train step (``train/__init__.py`` ``donate_argnums=(0,)``), the
cache-initialising first decode chunk ("prefill entry"), and the eval fn
were on the honor system.  This module builds a small audit model and
lowers + compiles all four on the CURRENT backend — on TPU that audits the
exact production executable; under the CPU rig it pins the structural
properties (donation, aliasable carries, collective count, no host syncs)
that the TPU compile inherits.

Each ``lower_*`` returns ``(hlo_text, context)`` where ``context`` carries
what the passes need: ``donated_leaves`` (expected alias count),
``protected`` (shapes whose full-buffer copy is a regression), and
``bf16_params`` for the dtype-promotion pass.  ``audit_all`` runs every
pass over every entry point against ``analysis/budgets.json``.

jax is imported inside functions only — importing this module stays cheap
(and safe from the AST-only consumers of the package).
"""
from __future__ import annotations

import typing

from . import hlo_lint

#: the audit model: small enough that all four compiles finish in seconds
#: on one CPU, in bf16 so the dtype-promotion pass has teeth (a param-
#: shaped f32 convert in a bf16 forward is an accidental master-weight
#: copy).  Mirrors tests/backend.py's harness config.
AUDIT_CONFIG: typing.Dict[str, typing.Any] = {
    "model_mode": "gpt", "use_video": False, "use_language": True,
    "sequence_length": 16, "features_per_head": 16, "heads": 2,
    "depth": 2, "train_batch_size": 4, "vocab_size": 32,
    "group_linear_factor": 2,
    "intermediate_feed_forward_multiplier_multiplier": 0.5,
    "calculation_dtype": "bfloat16", "storage_dtype": "bfloat16",
    "memory_reduction_strategy": "none",
    # the flagship optimizer chain (bench.py): its sm3/momentum slots put
    # real optimizer state into the donated carry, so the donation audit
    # covers opt-state aliasing too, not just params
    "optimizer": "adaptive_clip:0.003-sm3-momentum:0.9:1:1-learning_rate",
    "block_config": [
        {"layer": ["norm-shift-scale-features-group",
                   "bottleneck_group_linear-in:relu-mid:relu-mid:norm-mid:"
                   "shift-mid:scale-mid:features"]},
        {"layer": ["norm-shift-scale-features-group",
                   "attention-biased_attention_map-absolute-input_as_value-"
                   "shared",
                   "norm-shift-scale-features-group", "activation-gelu",
                   "attention-biased_attention_map-absolute-input_as_value-"
                   "shared"]}],
}

#: audited entry points, in budgets.json key order.  The four ``*_chunk_
#: step`` tails mirror ``infer/engine.py`` ``ENGINE_PROGRAMS`` — the
#: Engine's composition registry (mirrored, not imported: this module must
#: import without jax; the static-analysis tests pin the two in sync)
ENTRY_POINTS = ("train_step", "decode_chunk_step", "prefill_entry_step",
                "eval_fn", "engine_chunk_step", "spec_chunk_step",
                "paged_chunk_step", "spec_paged_chunk_step")

#: KV block size for the paged-engine audit: a real multi-block geometry
#: (seq 16 -> 4 blocks/slot) so the table gather/scatter machinery is
#: present in the audited module, not degenerate single-block paging
PAGED_AUDIT_BLOCK_TOKENS = 4

#: the speculative DRAFT at audit scale: the same model definition at a
#: smaller width (the one-graph-many-layouts rule the production draft
#: config follows; features_per_head 8 is the narrowest width the audit
#: architecture's factorized vocab supports)
DRAFT_AUDIT_OVERRIDES: typing.Dict[str, typing.Any] = {
    "features_per_head": 8}


def build_audit_model(overrides: typing.Optional[dict] = None, seed: int = 0):
    """(params, model, variables, token_x, batch) at the audit config."""
    import jax.numpy as jnp
    import numpy as np

    from ..config import ModelParameter
    from ..model import Model

    cfg = dict(AUDIT_CONFIG)
    cfg.update(overrides or {})
    params = ModelParameter(cfg)
    model = Model(params)
    rng = np.random.default_rng(seed)
    seq = params.sequence_dim.size
    tps = params.token_patch_dim.size
    token_x = rng.integers(0, params.vocab_size,
                           (params.train_batch_size, seq, tps)
                           ).astype(np.int32)
    batch = {"token_x": jnp.asarray(token_x),
             "token_y": jnp.asarray(token_x)}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    return params, model, variables, token_x, batch


# ---- entry-point lowerings -------------------------------------------------

def make_trainer(params, model, batch):
    """One ``(trainer, state)`` shared by every train-side lowering —
    ``init_state`` materialises params + optimizer state, so ``audit_all``
    pays it once instead of per entry point."""
    from ..train import Trainer

    trainer = Trainer(params, model)
    return trainer, trainer.init_state(batch)


def lower_train_step(params, model, variables, batch, donate: bool = True,
                     trainer=None, state=None):
    """Compiled donated train step.  ``donate=False`` compiles the same
    step UNdonated — the negative control proving the donation audit bites
    on real HLO, not only on synthetic text."""
    import jax

    if trainer is None:
        trainer, state = make_trainer(params, model, batch)
    if donate:
        lowered = trainer.lowered(state, batch)
    else:
        lowered = trainer._build_step(donate=False).lower(
            state, batch, jax.random.PRNGKey(0))
    compiled = lowered.compile()
    hlo = compiled.as_text()
    leaves = jax.tree_util.tree_leaves(state)
    context = {
        "donated_leaves": len(leaves) if donate else 0,
        # a full copy of any param/optimizer-state leaf is the train-side
        # analogue of the full-cache decode copy (2x HBM on the biggest
        # buffers in the program)
        "protected": hlo_lint.shape_strings(
            {str(i): leaf for i, leaf in enumerate(leaves)}, min_rank=2),
        "donated_bytes": sum(leaf.size * leaf.dtype.itemsize
                             for leaf in leaves),
        "state": state,
        "compiled": compiled,
        # jaxpr thunk for the cost ledger's analytical per-scope counts —
        # tracing is cheap next to the compile above, and only the ledger
        # pays it.  Undonated: donation changes aliasing, never flops.
        "trace": lambda: trainer._build_step(donate=False).trace(
            state, batch, jax.random.PRNGKey(0)).jaxpr,
    }
    return hlo, context


def lower_eval_fn(params, model, variables, batch, trainer=None, state=None):
    """Compiled forward-only eval fn (no donation expected — variables are
    reused across eval batches; the audit pins collectives + host syncs +
    bf16 discipline)."""
    if trainer is None:
        trainer, state = make_trainer(params, model, batch)
    compiled = trainer.lowered_eval(state, batch).compile()
    hlo = compiled.as_text()
    context = {
        "donated_leaves": 0,
        "bf16_params": hlo_lint.shape_strings(variables, min_rank=2,
                                              dtypes={"bf16"}),
        "compiled": compiled,
        "trace": lambda: trainer._eval_fn.trace(state.variables,
                                                batch).jaxpr,
    }
    return hlo, context


def lower_decode_step(model, variables, token_x, logits_filter: bool = False,
                      mesh=None):
    """Compiled donated decode chunk step (the PR 2 property: every cache
    leaf aliased, no full-cache-shaped copy).

    Uses the zero-cache layout from ``decode_cache_shapes`` (the layout the
    stepped driver carries) and abstract avals throughout: ``lower()``
    needs shapes/dtypes only, and materialising the caches would allocate
    the multi-GB buffers this check exists to police — running it next to
    a live serving deployment must not OOM the chip.
    """
    import jax
    import jax.numpy as jnp

    from ..infer.sampler import decode_cache_shapes, make_kv_step

    aval = jax.ShapeDtypeStruct
    batch = token_x.shape[0]
    shapes = decode_cache_shapes(model, variables, token_x)
    caches = {k: aval(v.shape, v.dtype) for k, v in shapes.items()}
    step = jax.jit(make_kv_step(model, mesh=mesh,
                                logits_filter=logits_filter),
                   donate_argnums=(6,))
    scalar = aval((), jnp.int32)
    fargs = _filter_args(batch, logits_filter)
    key = aval(jax.random.PRNGKey(0).shape, jnp.uint32)
    carry = (scalar, aval(tuple(token_x.shape), token_x.dtype), caches, key)
    if logits_filter:
        carry = carry + (aval((batch, model.params.vocab_size),
                              jnp.float32),)
    args = (variables, aval((batch,), jnp.int32),
            aval((batch,), jnp.float32), scalar, scalar, fargs, carry)
    compiled = step.lower(*args).compile()
    hlo = compiled.as_text()
    # the donated carry has EXACTLY len(shapes) cache leaves + q + token_x
    # + key (+ seen under the filter); requiring that many aliases means
    # every leaf aliased — a count any cache leaf could miss only by
    # another, nonexistent leaf standing in for it
    context = {
        "donated_leaves": len(shapes) + 3 + (1 if logits_filter else 0),
        "protected": hlo_lint.shape_strings(shapes, key_filter="/kv"),
        "cache_shapes": shapes,
        "bf16_params": hlo_lint.shape_strings(variables, min_rank=2,
                                              dtypes={"bf16"}),
        "compiled": compiled,
        "trace": lambda: step.trace(*args).jaxpr,
    }
    return hlo, context


def lower_prefill_entry(model, variables, token_x,
                        logits_filter: bool = False, mesh=None,
                        donate: bool = True):
    """Compiled cache-initialising first chunk (``kv_step_init`` — the
    entry the prefill/steady split hands the donated carry to).  Its carry
    omits the caches (built in-trace, mesh-constrained by the first decode
    step) but q/token_x/key (+ seen) are still donated and must alias.

    ``donate=False`` compiles the same step UNdonated — the negative
    control for this entry point's donation audit.  The returned context
    keeps the donated-case expectation either way, so the control asserts
    the audit FLAGS the undonated module against it."""
    import jax
    import jax.numpy as jnp

    from ..infer.sampler import decode_cache_shapes, make_kv_step

    aval = jax.ShapeDtypeStruct
    batch = token_x.shape[0]
    shapes = decode_cache_shapes(model, variables, token_x)
    step = jax.jit(make_kv_step(model, mesh=mesh,
                                logits_filter=logits_filter,
                                init_caches=True),
                   donate_argnums=(6,) if donate else ())
    scalar = aval((), jnp.int32)
    fargs = _filter_args(batch, logits_filter)
    key = aval(jax.random.PRNGKey(0).shape, jnp.uint32)
    carry = (scalar, aval(tuple(token_x.shape), token_x.dtype), key)
    if logits_filter:
        carry = carry + (aval((batch, model.params.vocab_size),
                              jnp.float32),)
    args = (variables, aval((batch,), jnp.int32),
            aval((batch,), jnp.float32), scalar, scalar, fargs, carry)
    compiled = step.lower(*args).compile()
    hlo = compiled.as_text()
    context = {
        "donated_leaves": 3 + (1 if logits_filter else 0),
        "protected": hlo_lint.shape_strings(shapes, key_filter="/kv"),
        "bf16_params": hlo_lint.shape_strings(variables, min_rank=2,
                                              dtypes={"bf16"}),
        "compiled": compiled,
        "trace": lambda: step.trace(*args).jaxpr,
    }
    return hlo, context


def lower_engine_step(model, variables, token_x, mesh=None):
    """Compiled donated continuous-batching engine chunk step — the
    slot-pool analogue of ``decode_chunk_step``: the donated carry holds the
    ENTIRE fixed-slot KV pool (per-slot rows of every cache leaf), and the
    audit pins that every pool leaf aliases input->output with no
    full-pool-shaped copy, per-slot position vector and all
    (infer/engine.py; docs/SERVING.md).

    Audits the steady-state ``engine_plain`` variant — the program every
    decode chunk between admissions runs; abstract avals throughout, same
    OOM-safety argument as ``lower_decode_step``.
    """
    import jax
    import jax.numpy as jnp

    from ..infer.engine import _engine_jit
    from ..infer.sampler import decode_cache_shapes

    aval = jax.ShapeDtypeStruct
    batch = token_x.shape[0]
    shapes = decode_cache_shapes(model, variables, token_x)
    caches = {k: aval(v.shape, v.dtype) for k, v in shapes.items()}
    step = _engine_jit(model, mesh, "engine_plain")
    vec_i = aval((batch,), jnp.int32)
    vec_f = aval((batch,), jnp.float32)
    scalar = aval((), jnp.int32)
    key = aval(jax.random.PRNGKey(0).shape, jnp.uint32)
    seen = aval((batch, model.params.vocab_size), jnp.float32)
    carry = (vec_i, aval(tuple(token_x.shape), token_x.dtype), caches, key,
             seen)
    fargs = (vec_i, vec_f, vec_f)
    args = (variables, vec_i, vec_f, vec_i, scalar, fargs, (), carry)
    compiled = step.lower(*args).compile()
    hlo = compiled.as_text()
    context = {
        # q + token_x + key + seen ride the donated carry next to the pool
        "donated_leaves": len(shapes) + 4,
        "protected": hlo_lint.shape_strings(shapes, key_filter="/kv"),
        "cache_shapes": shapes,
        "bf16_params": hlo_lint.shape_strings(variables, min_rank=2,
                                              dtypes={"bf16"}),
        "compiled": compiled,
        "trace": lambda: step.trace(*args).jaxpr,
    }
    return hlo, context


def lower_paged_step(model, variables, token_x, mesh=None):
    """Compiled donated PAGED engine chunk step (``infer/paged.py``
    ``_paged_jit`` kind ``paged_plain``): the donated carry holds the KV
    BLOCK POOLS (per-leaf ``[num_blocks, block_tokens, ...]`` layouts plus
    any resident recurrent leaves), and the chunk gathers per-slot views
    through the read table, runs the shared engine loop, and scatters back
    through the write table.  The audit pins every pool leaf aliased
    input->output with no full-pool-shaped copy — the gather/scatter
    round-trip must not cost a resident duplicate of the pool.

    Abstract avals throughout, same OOM-safety argument as
    ``lower_decode_step``."""
    import jax
    import jax.numpy as jnp

    from ..infer.paged import _paged_jit, classify_cache_leaves
    from ..infer.sampler import decode_cache_shapes

    aval = jax.ShapeDtypeStruct
    batch, seq = token_x.shape[0], token_x.shape[1]
    bt = PAGED_AUDIT_BLOCK_TOKENS if seq % PAGED_AUDIT_BLOCK_TOKENS == 0 \
        else 1
    seq_blocks = seq // bt
    num_blocks = batch * seq_blocks
    shapes = decode_cache_shapes(model, variables, token_x)
    info = classify_cache_leaves(shapes, seq)
    pools = {}
    for n, s in shapes.items():
        baxis, sax = info[n]
        if sax is None:
            pools[n] = aval(tuple(s.shape), s.dtype)
        else:
            ps = list(s.shape)
            ps[baxis], ps[sax] = num_blocks, bt
            pools[n] = aval(tuple(ps), s.dtype)
    step = _paged_jit(model, mesh, "paged_plain", bt, num_blocks)
    vec_i = aval((batch,), jnp.int32)
    vec_f = aval((batch,), jnp.float32)
    scalar = aval((), jnp.int32)
    key = aval(jax.random.PRNGKey(0).shape, jnp.uint32)
    seen = aval((batch, model.params.vocab_size), jnp.float32)
    table = aval((batch, seq_blocks), jnp.int32)
    carry = (vec_i, aval(tuple(token_x.shape), token_x.dtype), pools, key,
             seen)
    fargs = (vec_i, vec_f, vec_f)
    args = (variables, vec_i, vec_f, vec_i, scalar, fargs, (), table, table,
            carry)
    compiled = step.lower(*args).compile()
    hlo = compiled.as_text()
    context = {
        # q + token_x + key + seen ride the donated carry next to the pools
        "donated_leaves": len(pools) + 4,
        "protected": hlo_lint.shape_strings(pools, key_filter="/kv"),
        "cache_shapes": pools,
        "bf16_params": hlo_lint.shape_strings(variables, min_rank=2,
                                              dtypes={"bf16"}),
        "compiled": compiled,
        "trace": lambda: step.trace(*args).jaxpr,
    }
    return hlo, context


def lower_spec_step(model, variables, token_x, draft_model=None,
                    draft_variables=None, mesh=None):
    """Compiled donated SPECULATIVE chunk step (``infer/engine.py``
    ``_spec_jit`` kind ``spec_plain`` — k+1 draft steps + one width-(k+1)
    verify in a single program): the donated carry holds BOTH cache pools
    — the target's slot pool AND the quarter-width draft's — and the audit
    pins every leaf of both aliased input->output with no full-pool-shaped
    copy.  The verify's sampled-token readback is the only fresh output.

    ``draft_model``/``draft_variables`` default to a fresh
    ``DRAFT_AUDIT_OVERRIDES`` build; abstract avals throughout, same
    OOM-safety argument as ``lower_decode_step``.
    """
    import jax
    import jax.numpy as jnp

    from ..infer.engine import _spec_jit
    from ..infer.sampler import decode_cache_shapes

    if draft_model is None:
        _, draft_model, draft_variables, _, _ = build_audit_model(
            DRAFT_AUDIT_OVERRIDES, seed=1)
    aval = jax.ShapeDtypeStruct
    batch = token_x.shape[0]
    tps = token_x.shape[2]
    tshapes = decode_cache_shapes(model, variables, token_x)
    dshapes = decode_cache_shapes(draft_model, draft_variables, token_x)
    caches = {k: aval(v.shape, v.dtype) for k, v in tshapes.items()}
    dcaches = {k: aval(v.shape, v.dtype) for k, v in dshapes.items()}
    step = _spec_jit(model, draft_model, mesh, "spec_plain",
                     model.params.spec_draft_tokens)
    vec_i = aval((batch,), jnp.int32)
    vec_f = aval((batch,), jnp.float32)
    vec_b = aval((batch,), jnp.bool_)
    key = aval(jax.random.PRNGKey(0).shape, jnp.uint32)
    seen = aval((batch, model.params.vocab_size), jnp.float32)
    carry = (aval(tuple(token_x.shape), token_x.dtype), caches, dcaches,
             key, seen)
    fargs = (vec_i, vec_f, vec_f)
    args = (variables, draft_variables, vec_i, vec_i, vec_f, vec_i, fargs,
            vec_b, aval((batch, tps), jnp.int32), vec_b, vec_i, (), carry)
    compiled = step.lower(*args).compile()
    hlo = compiled.as_text()
    context = {
        # token_x + key + seen ride the donated carry next to the two pools
        "donated_leaves": len(tshapes) + len(dshapes) + 3,
        "protected": (hlo_lint.shape_strings(tshapes, key_filter="/kv")
                      | hlo_lint.shape_strings(dshapes, key_filter="/kv")),
        # the two pools share cache key names (same scope paths at two
        # widths): namespace the draft's for consumers that need a flat map
        "cache_shapes": {**tshapes,
                         **{"draft/" + k: v for k, v in dshapes.items()}},
        "bf16_params": (hlo_lint.shape_strings(variables, min_rank=2,
                                               dtypes={"bf16"})
                        | hlo_lint.shape_strings(draft_variables, min_rank=2,
                                                 dtypes={"bf16"})),
        "compiled": compiled,
        "trace": lambda: step.trace(*args).jaxpr,
    }
    return hlo, context


def lower_spec_paged_step(model, variables, token_x, draft_model=None,
                          draft_variables=None, mesh=None):
    """Compiled donated SPEC-ON-PAGED chunk step — the composed program
    (``infer/engine.py`` ``ENGINE_PROGRAMS["spec_paged_chunk_step"]``):
    draft + width-(k+1) verify running over BLOCK POOLS for BOTH models,
    gathered/scattered through the same read/write tables.  The donated
    carry holds both pools at block geometry plus token_x/key/seen; the
    audit pins every leaf of both pools aliased input->output with no
    full-pool-shaped copy — composing the components must not cost a
    resident duplicate of either pool.

    Abstract avals throughout, same OOM-safety argument as
    ``lower_decode_step``."""
    import jax
    import jax.numpy as jnp

    from ..infer.engine import _chunk_jit
    from ..infer.paged import classify_cache_leaves
    from ..infer.sampler import decode_cache_shapes

    if draft_model is None:
        _, draft_model, draft_variables, _, _ = build_audit_model(
            DRAFT_AUDIT_OVERRIDES, seed=1)
    aval = jax.ShapeDtypeStruct
    batch, seq = token_x.shape[0], token_x.shape[1]
    tps = token_x.shape[2]
    bt = PAGED_AUDIT_BLOCK_TOKENS if seq % PAGED_AUDIT_BLOCK_TOKENS == 0 \
        else 1
    seq_blocks = seq // bt
    num_blocks = batch * seq_blocks

    def block_pools(shapes):
        info = classify_cache_leaves(shapes, seq)
        pools = {}
        for n, s in shapes.items():
            baxis, sax = info[n]
            if sax is None:
                pools[n] = aval(tuple(s.shape), s.dtype)
            else:
                ps = list(s.shape)
                ps[baxis], ps[sax] = num_blocks, bt
                pools[n] = aval(tuple(ps), s.dtype)
        return pools

    tshapes = decode_cache_shapes(model, variables, token_x)
    dshapes = decode_cache_shapes(draft_model, draft_variables, token_x)
    tpools = block_pools(tshapes)
    dpools = block_pools(dshapes)
    step = _chunk_jit(model, mesh, "plain", draft_model=draft_model,
                      k=model.params.spec_draft_tokens,
                      paged=(bt, num_blocks))
    vec_i = aval((batch,), jnp.int32)
    vec_f = aval((batch,), jnp.float32)
    vec_b = aval((batch,), jnp.bool_)
    key = aval(jax.random.PRNGKey(0).shape, jnp.uint32)
    seen = aval((batch, model.params.vocab_size), jnp.float32)
    table = aval((batch, seq_blocks), jnp.int32)
    carry = (aval(tuple(token_x.shape), token_x.dtype), tpools, dpools,
             key, seen)
    fargs = (vec_i, vec_f, vec_f)
    args = (variables, draft_variables, vec_i, vec_i, vec_f, vec_i, fargs,
            vec_b, aval((batch, tps), jnp.int32), vec_b, vec_i, (), table,
            table, carry)
    compiled = step.lower(*args).compile()
    hlo = compiled.as_text()
    context = {
        # token_x + key + seen ride the donated carry next to the two pools
        "donated_leaves": len(tpools) + len(dpools) + 3,
        "protected": (hlo_lint.shape_strings(tpools, key_filter="/kv")
                      | hlo_lint.shape_strings(dpools, key_filter="/kv")),
        "cache_shapes": {**tpools,
                         **{"draft/" + k: v for k, v in dpools.items()}},
        "bf16_params": (hlo_lint.shape_strings(variables, min_rank=2,
                                               dtypes={"bf16"})
                        | hlo_lint.shape_strings(draft_variables, min_rank=2,
                                                 dtypes={"bf16"})),
        "compiled": compiled,
        "trace": lambda: step.trace(*args).jaxpr,
    }
    return hlo, context


def _filter_args(batch: int, logits_filter: bool):
    import jax
    import jax.numpy as jnp
    aval = jax.ShapeDtypeStruct
    if not logits_filter:
        return ()
    return (aval((batch,), jnp.int32), aval((batch,), jnp.float32),
            aval((batch,), jnp.float32))


# ---- one-call audit --------------------------------------------------------

def lower_all(overrides: typing.Optional[dict] = None
              ) -> "typing.Dict[str, typing.Tuple[str, dict]]":
    """``{entry: (hlo_text, context)}`` for every registered entry point,
    from ONE shared audit model + trainer build.  Contexts carry the
    ``compiled`` executable (for ``cost_analysis``) and a ``trace`` thunk
    producing the entry's jaxpr — the cost ledger (analysis/cost_ledger.py)
    and the HLO audits below consume the same compiles, so running both in
    ``graft_lint --hlo`` pays the four compiles once."""
    import jax.numpy as jnp

    params, model, variables, token_x, batch = build_audit_model(overrides)
    trainer, state = make_trainer(params, model, batch)
    out: typing.Dict[str, typing.Tuple[str, dict]] = {}
    out["train_step"] = lower_train_step(params, model, variables, batch,
                                         trainer=trainer, state=state)
    out["decode_chunk_step"] = lower_decode_step(model, variables,
                                                 jnp.asarray(token_x))
    out["prefill_entry_step"] = lower_prefill_entry(model, variables,
                                                    jnp.asarray(token_x))
    out["eval_fn"] = lower_eval_fn(params, model, variables, batch,
                                   trainer=trainer, state=state)
    out["engine_chunk_step"] = lower_engine_step(model, variables,
                                                 jnp.asarray(token_x))
    out["paged_chunk_step"] = lower_paged_step(model, variables,
                                               jnp.asarray(token_x))
    draft_overrides = dict(overrides or {})
    draft_overrides.update(DRAFT_AUDIT_OVERRIDES)
    _, dmodel, dvariables, _, _ = build_audit_model(draft_overrides, seed=1)
    out["spec_chunk_step"] = lower_spec_step(model, variables,
                                             jnp.asarray(token_x),
                                             draft_model=dmodel,
                                             draft_variables=dvariables)
    out["spec_paged_chunk_step"] = lower_spec_paged_step(
        model, variables, jnp.asarray(token_x), draft_model=dmodel,
        draft_variables=dvariables)
    return out


def lower_one(entry: str, overrides: typing.Optional[dict] = None
              ) -> typing.Tuple[str, dict]:
    """``(hlo_text, context)`` for ONE entry point — what
    ``scripts/attribute_step.py`` uses so a single-entry trace join pays
    one compile, not four."""
    import jax.numpy as jnp

    if entry not in ENTRY_POINTS:
        raise ValueError(f"unknown entry point {entry!r}; one of "
                         f"{ENTRY_POINTS}")
    params, model, variables, token_x, batch = build_audit_model(overrides)
    if entry in ("train_step", "eval_fn"):
        trainer, state = make_trainer(params, model, batch)
        if entry == "train_step":
            return lower_train_step(params, model, variables, batch,
                                    trainer=trainer, state=state)
        return lower_eval_fn(params, model, variables, batch,
                             trainer=trainer, state=state)
    if entry == "decode_chunk_step":
        return lower_decode_step(model, variables, jnp.asarray(token_x))
    if entry == "engine_chunk_step":
        return lower_engine_step(model, variables, jnp.asarray(token_x))
    if entry == "paged_chunk_step":
        return lower_paged_step(model, variables, jnp.asarray(token_x))
    if entry in ("spec_chunk_step", "spec_paged_chunk_step"):
        # the draft shares the caller's overrides (sequence geometry must
        # match the target — the lower_all merge rule)
        draft_overrides = dict(overrides or {})
        draft_overrides.update(DRAFT_AUDIT_OVERRIDES)
        _, dmodel, dvariables, _, _ = build_audit_model(draft_overrides,
                                                        seed=1)
        lower = (lower_spec_step if entry == "spec_chunk_step"
                 else lower_spec_paged_step)
        return lower(model, variables, jnp.asarray(token_x),
                     draft_model=dmodel, draft_variables=dvariables)
    return lower_prefill_entry(model, variables, jnp.asarray(token_x))


def audit_lowered(lowered: "typing.Dict[str, typing.Tuple[str, dict]]",
                  budgets: typing.Optional[dict] = None
                  ) -> typing.List[hlo_lint.Finding]:
    """Every HLO pass over pre-lowered entry points (``lower_all``).
    Donation audit covers all four (eval's expectation is zero — a donation
    appearing there would be a bug of its own kind, but zero aliases is its
    honest baseline); the dtype-promotion pass skips the train step, where
    the optimizer's f32 slice dtype legitimately promotes param-shaped
    grads."""
    budgets = budgets if budgets is not None else hlo_lint.load_budgets()
    per_entry = budgets.get("entry_points", {})
    findings: typing.List[hlo_lint.Finding] = []

    hlo, ctx = lowered["train_step"]
    train_budget = per_entry.get("train_step", {})
    findings += hlo_lint.audit(
        "train_step", hlo,
        expected_aliases=ctx["donated_leaves"],
        protected_shapes=ctx["protected"],
        max_copied_bytes=int(train_budget.get("copy_byte_fraction", 0.0)
                             * ctx["donated_bytes"]),
        budget=train_budget)

    for entry in ("decode_chunk_step", "prefill_entry_step",
                  "engine_chunk_step", "spec_chunk_step",
                  "paged_chunk_step", "spec_paged_chunk_step"):
        hlo, ctx = lowered[entry]
        findings += hlo_lint.audit(
            entry, hlo,
            expected_aliases=ctx["donated_leaves"],
            protected_shapes=ctx["protected"],
            bf16_param_shapes=ctx["bf16_params"],
            budget=per_entry.get(entry, {}))

    hlo, ctx = lowered["eval_fn"]
    findings += hlo_lint.audit(
        "eval_fn", hlo,
        expected_aliases=ctx["donated_leaves"],
        bf16_param_shapes=ctx["bf16_params"],
        budget=per_entry.get("eval_fn", {}))

    return findings


def audit_all(overrides: typing.Optional[dict] = None,
              budgets: typing.Optional[dict] = None
              ) -> typing.List[hlo_lint.Finding]:
    """``audit_lowered(lower_all(overrides))`` — the one-call form tier-1
    and older callers use."""
    return audit_lowered(lower_all(overrides), budgets)
