"""graft-lint: compiled-artifact + AST static-analysis layer.

The properties that keep a TPU program fast — donation actually aliasing,
no full-buffer copies, no stray collectives from accidental resharding —
live in the COMPILED module, not the traced one, and regress silently
(BASELINE.md round 5: the fused decode loop traced identically at 0.5 GB
and 6.5 GB yet only aliased at the former).  This package audits them
mechanically for every jitted entry point instead of one-off per PR:

- ``hlo_lint``   — parameterized passes over compiled-HLO text (donation
  audit, big-copy detection, dtype-promotion audit, collective census vs
  ``budgets.json``, host-sync detection).  Stdlib-only at import; jax is
  needed only to produce the HLO you feed it.
- ``ast_lint``   — repo-specific source rules (wall-clock discipline,
  unseeded rngs, donated-jit registration, config-docs coverage).
  Stdlib-only and importable standalone (scripts/check_config_docs.py
  loads it without the package).
- ``entry_points`` — builds a small audit model on the current backend and
  lowers the registered jitted entry points (train step, decode chunk
  step, prefill-entry step, eval fn, engine chunk step) for the HLO
  passes.
- ``mesh_audit``  — lowers the entry points under every parallel strategy
  (dp x tp, ring SP, MoE EP, the pipeline schedules) on 8 virtual CPU
  devices and audits per-mesh collective budgets, sharding contracts,
  and peak-HBM liveness against the ``meshes`` section of
  ``budgets.json``.
- ``cost_ledger`` — per-entry, per-scope analytical flops/bytes ledger
  regression-checked against ``cost_ledger.json``.

Run everything: ``python scripts/graft_lint.py --all`` (docs/STATIC_ANALYSIS.md).
"""
from . import ast_lint, hlo_lint  # noqa: F401

__all__ = ["ast_lint", "hlo_lint", "entry_points", "mesh_audit",
           "cost_ledger"]


def __getattr__(name):
    # entry_points imports model/train/infer machinery (and, inside its
    # functions, jax); load it lazily so `import homebrewnlp_tpu.analysis`
    # stays cheap for AST-only consumers
    if name in ("entry_points", "mesh_audit", "cost_ledger"):
        import importlib
        return importlib.import_module("." + name, __name__)
    raise AttributeError(name)
