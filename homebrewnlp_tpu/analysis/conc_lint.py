"""Host-concurrency audit (graft-lint ``--conc``, half 1 + 3).

The serving/elastic control plane's guarantees (exactly-one-answer,
never-a-500, refcount conservation, lease liveness) live in host Python
threads, and their correctness rests on lock discipline that — unlike
the compiled-artifact contracts the other graft-lint halves pin — was
enforced only by convention.  This module makes the convention a checked
declaration:

* ``GUARDED_BY`` — per audited class, which lock guards which
  attributes.  Rule ``lock-guard`` flags any access to a guarded
  attribute outside a ``with <lock>`` scope on the same object
  (``__init__`` is exempt: attribute establishment precedes sharing).
  Mode ``"rw"`` checks reads and writes; ``"w"`` checks writes only —
  for benignly-racy monotonic reads (``Replica.inflight`` load-balance
  hints) where a torn read degrades a heuristic, never an invariant.
* Rule ``lock-blocking`` — blocking calls (file IO, ``time.sleep``,
  subprocess, sockets/urlopen, queue get/put, IPC recv/send) inside any
  ``with <...lock>`` scope: a blocked lock-holder stalls every thread
  behind it (and a flush path that blocks under the recorder lock stalls
  the signal handler that shares it).
* Rule ``lock-order`` — nested ``with``-lock scopes build a cross-module
  acquisition graph; a cycle is a deadlock the OS scheduler will
  eventually find.  The graph merges three views: this static pass, the
  interleaving explorer's observed edges (``analysis/interleave.py``),
  and opt-in runtime traces from real marker-suite runs
  (``utils/locks.py``, ``HBNLP_LOCK_TRACE``).
* Rule ``thread-hygiene`` — every ``threading.Thread`` needs an explicit
  ``name=`` (forensics blackbox events carry the thread name) and a
  deliberate ``daemon=`` choice; a ``daemon=False`` thread additionally
  needs a ``join`` somewhere on the file's exit paths.

Same idiom as ``ast_lint``: stdlib-only, ``Finding`` rows, rule-scoped
``graft-lint: allow[rule]`` suppressions on the flagged line or the line
above.  Onboarding protocol for new guarded classes is documented in
docs/STATIC_ANALYSIS.md 'Concurrency audit'.
"""
from __future__ import annotations

import ast
import collections
import glob as _glob
import json
import os
import typing

from .ast_lint import (Finding, LINT_SUBDIRS, REPO, _dotted, _suppressed,
                       iter_source_files)

__all__ = [
    "GUARDED_BY", "lint_source", "lint_repo_conc", "order_findings",
    "registry_findings", "explorer_findings", "load_trace_edges",
    "trace_findings",
]


# ---------------------------------------------------------------- registry

#: "relpath::Class" -> {"lock": attr, "guards": {attr: "rw"|"w"},
#: "aliases": (attrs,)} — aliases are lock-sharing handles (a Condition
#: built over the same lock).  Declaring a class here is a CONTRACT: the
#: lint enforces it forever after (onboarding protocol:
#: docs/STATIC_ANALYSIS.md 'Concurrency audit').  Deliberately-unlocked
#: attrs stay undeclared with the reason recorded here:
#: ``_Metric._children`` (racing creators build equal children; last
#: write wins into the same ``_series`` slot) and ``Router._last_index_sync``
#: (poll-loop throttle; a torn read costs one extra best-effort scrape).
GUARDED_BY: typing.Dict[str, dict] = {
    "homebrewnlp_tpu/infer/router.py::Replica": {
        "lock": "_lock",
        "guards": {"inflight": "w", "requests": "w", "failures": "w"},
    },
    "homebrewnlp_tpu/infer/router.py::GlobalPrefixIndex": {
        "lock": "_lock",
        "guards": {"_map": "rw", "_gen": "rw"},
    },
    "homebrewnlp_tpu/infer/router.py::Router": {
        "lock": "_lock",
        "guards": {"_affinity": "rw"},
    },
    "homebrewnlp_tpu/telemetry/events.py::FlightRecorder": {
        "lock": "_lock",  # RLock: the SIGUSR2 handler re-enters flush
        "guards": {"_events": "rw", "_seq": "rw", "_dirty": "rw",
                   "_last_flush": "rw", "model_path": "w", "tag": "w"},
    },
    "homebrewnlp_tpu/telemetry/spans.py::ChromeTrace": {
        "lock": "_lock",
        "guards": {"_events": "rw"},
    },
    "homebrewnlp_tpu/telemetry/registry.py::_Metric": {
        "lock": "_lock",
        "guards": {"_series": "rw"},
    },
    "homebrewnlp_tpu/telemetry/registry.py::Registry": {
        "lock": "_lock",
        "guards": {"_metrics": "rw"},
    },
    "homebrewnlp_tpu/distributed/async_checkpoint.py::AsyncCheckpointer": {
        "lock": "_lock",
        "aliases": ("_idle",),  # Condition(self._lock): same mutex
        "guards": {"_error": "rw", "_inflight": "rw"},
    },
}


def registry_findings(root: str = REPO,
                      registry: typing.Dict[str, dict] = GUARDED_BY
                      ) -> typing.List[Finding]:
    """Rule ``conc-registry``: every GUARDED_BY key must point at a real
    file, class, and lock attribute — a stale entry silently audits
    nothing."""
    out = []
    for key, spec in registry.items():
        rel, _, cls = key.partition("::")
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            out.append(Finding("conc-registry", key,
                               f"file {rel} does not exist"))
            continue
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            out.append(Finding("conc-registry", key,
                               f"cannot parse {rel}: {e}"))
            continue
        node = next((n for n in ast.walk(tree)
                     if isinstance(n, ast.ClassDef) and n.name == cls),
                    None)
        if node is None:
            out.append(Finding("conc-registry", key,
                               f"class {cls} not found in {rel}"))
            continue
        lock = spec.get("lock", "_lock")
        assigned = {t.attr for n in ast.walk(node)
                    for t in ast.walk(n)
                    if isinstance(t, ast.Attribute)
                    and isinstance(t.ctx, ast.Store)
                    and _dotted(t.value) == "self"}
        for attr in [lock, *spec.get("aliases", ()),
                     *spec.get("guards", {})]:
            if attr not in assigned:
                out.append(Finding(
                    "conc-registry", key,
                    f"attribute {attr!r} is never assigned on "
                    f"self in class {cls}"))
    return out


# ------------------------------------------------------- per-file analysis

#: pure path helpers on the ``utils.fs`` alias — everything else on
#: ``fs.`` is filesystem IO
_FS_PURE = {"join", "basename", "dirname", "split", "splitext"}


def _blocking_reason(call: ast.Call) -> typing.Optional[str]:
    """Name of the blocking primitive this call hits, or None."""
    d = _dotted(call.func)
    if not d:
        return None
    parts = d.split(".")
    last = parts[-1]
    if d in ("time.sleep", "os.system", "open"):
        return d
    if last == "urlopen":
        return d
    if "subprocess" in parts[:-1] and last in (
            "run", "call", "check_call", "check_output", "Popen"):
        return d
    if parts[0] == "socket" and last in ("create_connection", "socket"):
        return d
    if parts[-2:-1] == ["fs"] and last not in _FS_PURE:
        return d
    if last in ("open_",):
        return d
    if last in ("get", "put", "get_nowait", "put_nowait") \
            and len(parts) >= 2 and ("queue" in parts[-2].lower()
                                     or parts[-2] in ("q", "_q")):
        return d
    if last in ("recv", "send", "sendall", "connect", "accept") \
            and len(parts) >= 2 and any(
                s in parts[-2].lower() for s in ("sock", "conn", "pipe")):
        return d
    if last == "join" and len(parts) >= 2 \
            and "thread" in parts[-2].lower():
        return d
    return None


def _lock_names_for(rel: str,
                    registry: typing.Dict[str, dict]) -> typing.Set[str]:
    """Lock + alias attribute names registered for ``rel`` (the
    ``lock-blocking``/``lock-order`` passes also match any name
    containing 'lock')."""
    names: typing.Set[str] = set()
    for key, spec in registry.items():
        if key.partition("::")[0] == rel:
            names.add(spec.get("lock", "_lock"))
            names.update(spec.get("aliases", ()))
    return names


class _ConcVisitor:
    """One file's lock-discipline walk.

    Tracks, per function, the set of dotted PREFIXES currently holding
    their lock (``with self._lock`` holds prefix ``self``; ``with
    m._lock`` holds ``m``) — a guarded access ``<prefix>.<attr>`` is
    legal only while its prefix holds.  Also collects nested-with
    acquisition edges and every blocking call made under any lock."""

    def __init__(self, rel: str, source: str,
                 registry: typing.Dict[str, dict]):
        self.rel = rel
        self.lines = source.splitlines()
        self.registry = registry
        self.module = os.path.splitext(os.path.basename(rel))[0]
        #: union of guarded attrs across classes registered for this file
        self.guards: typing.Dict[str, str] = {}
        for key, spec in registry.items():
            if key.partition("::")[0] == rel:
                self.guards.update(spec.get("guards", {}))
        self.lock_attrs = _lock_names_for(rel, registry)
        self.findings: typing.List[Finding] = []
        self.edges: typing.Set[typing.Tuple[str, str]] = set()
        self.class_stack: typing.List[str] = []
        self.fn_stack: typing.List[str] = []

    # -- helpers -------------------------------------------------------------

    def _add(self, rule: str, node: ast.AST, message: str) -> None:
        if _suppressed(self.lines, node.lineno, rule):
            return
        entry = self.rel
        if self.class_stack or self.fn_stack:
            scope = ".".join(self.class_stack + self.fn_stack[-1:])
            entry = f"{self.rel}:{scope}"
        self.findings.append(
            Finding(rule, entry, f"line {node.lineno}: {message}"))

    def _lock_of(self, expr: ast.AST) -> typing.Optional[
            typing.Tuple[str, str]]:
        """``(holder_prefix, canonical_name)`` when ``expr`` is a lock
        acquisition context, else None.  Lock-ish = a registered
        lock/alias attr, or any name whose last segment contains
        'lock'."""
        d = _dotted(expr)
        if not d:
            return None
        parts = d.split(".")
        last = parts[-1]
        if last not in self.lock_attrs and "lock" not in last.lower():
            return None
        prefix = ".".join(parts[:-1])  # "" for module-level lock names
        if prefix == "self" and self.class_stack:
            canon = f"{self.class_stack[-1]}.{last}"
        elif prefix:
            canon = f"{self.module}.{d}"
        else:
            canon = f"{self.module}.{last}"
        return prefix, canon

    # -- walk ----------------------------------------------------------------

    def visit_module(self, tree: ast.Module) -> None:
        self._walk_body(tree.body, held_prefixes=set(), held_canon=[],
                        in_init=False)

    def _walk_body(self, body, held_prefixes, held_canon, in_init):
        for node in body:
            self._walk(node, held_prefixes, held_canon, in_init)

    def _walk(self, node, held_prefixes, held_canon, in_init):
        if isinstance(node, ast.ClassDef):
            self.class_stack.append(node.name)
            # a class body starts a fresh locking context
            self._walk_body(node.body, set(), [], False)
            self.class_stack.pop()
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.fn_stack.append(node.name)
            init = in_init or node.name == "__init__"
            # a nested def runs LATER: locks held at definition time are
            # not held at call time
            self._walk_body(node.body, set(), [], init)
            self.fn_stack.pop()
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_prefixes = set(held_prefixes)
            new_canon = list(held_canon)
            for item in node.items:
                lk = self._lock_of(item.context_expr)
                if lk is None:
                    continue
                prefix, canon = lk
                for outer in new_canon:
                    if outer != canon:
                        self.edges.add((outer, canon))
                new_prefixes.add(prefix)
                new_canon.append(canon)
            # the context expressions themselves evaluate BEFORE the lock
            # is held
            for item in node.items:
                self._scan_expr(item.context_expr, held_prefixes,
                                held_canon, in_init)
            self._walk_body(node.body, new_prefixes, new_canon, in_init)
            return
        # generic statement: scan expressions at this level, recurse into
        # compound-statement bodies with the same held set
        for field in ast.iter_fields(node):
            value = field[1]
            items = value if isinstance(value, list) else [value]
            for item in items:
                # excepthandler/match_case are statement CONTAINERS, not
                # statements: recurse so `with lock:` inside an except
                # block keeps its held context
                if isinstance(item, (ast.stmt, ast.excepthandler)) or \
                        type(item).__name__ == "match_case":
                    self._walk(item, held_prefixes, held_canon, in_init)
                elif isinstance(item, ast.AST):
                    self._scan_expr(item, held_prefixes, held_canon,
                                    in_init)

    def _scan_expr(self, expr, held_prefixes, held_canon, in_init):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                reason = _blocking_reason(node)
                if reason is not None and held_canon:
                    self._add(
                        "lock-blocking", node,
                        f"blocking call {reason}() while holding "
                        f"{held_canon[-1]} — a stalled holder blocks "
                        "every thread behind the lock")
            if isinstance(node, ast.Attribute) and not in_init:
                mode = self.guards.get(node.attr)
                if mode is None:
                    continue
                prefix = _dotted(node.value)
                if prefix is None or prefix in held_prefixes:
                    continue
                if mode == "w" and isinstance(node.ctx, ast.Load):
                    continue
                kind = ("write to" if not isinstance(node.ctx, ast.Load)
                        else "read of")
                self._add(
                    "lock-guard", node,
                    f"{kind} guarded attribute {prefix}.{node.attr} "
                    f"outside `with {prefix}.<lock>` (GUARDED_BY "
                    "declares it lock-protected)")
            if isinstance(node, ast.Call) \
                    and _dotted(node.func) in ("threading.Thread",
                                               "_threading.Thread",
                                               "Thread"):
                self._thread_hygiene(node)

    def _thread_hygiene(self, call: ast.Call) -> None:
        kwargs = {kw.arg: kw.value for kw in call.keywords
                  if kw.arg is not None}
        if "name" not in kwargs:
            self._add("thread-hygiene", call,
                      "threading.Thread without name= — forensics "
                      "blackbox events carry the thread name")
        if "daemon" not in kwargs:
            self._add("thread-hygiene", call,
                      "threading.Thread without an explicit daemon= "
                      "(the lifetime choice must be deliberate)")
            return
        daemon = kwargs["daemon"]
        if isinstance(daemon, ast.Constant) and daemon.value is False \
                and ".join(" not in "\n".join(self.lines):
            self._add("thread-hygiene", call,
                      "non-daemon thread with no join() in this file — "
                      "it outlives every exit path")


def _analyze(rel: str, source: str,
             registry: typing.Dict[str, dict]
             ) -> typing.Tuple[typing.List[Finding],
                               typing.Set[typing.Tuple[str, str]]]:
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("parse", rel, f"syntax error: {e}")], set()
    v = _ConcVisitor(rel, source, registry)
    v.visit_module(tree)
    return v.findings, v.edges


def lint_source(rel: str, source: str,
                registry: typing.Optional[typing.Dict[str, dict]] = None
                ) -> typing.List[Finding]:
    """Single-source entry point (tests and negative controls): AST
    rules plus an ordering-cycle check over this source's own edges."""
    findings, edges = _analyze(
        rel, source, GUARDED_BY if registry is None else registry)
    return findings + order_findings(edges)


# ------------------------------------------------------------- lock order

def order_findings(edges: typing.Iterable[typing.Tuple[str, str]]
                   ) -> typing.List[Finding]:
    """Rule ``lock-order``: cycles in the merged acquisition graph.  One
    finding per distinct cycle, naming its lock sequence."""
    graph: typing.Dict[str, typing.Set[str]] = collections.defaultdict(set)
    for a, b in edges:
        graph[a].add(b)
    out = []
    seen_cycles: typing.Set[typing.Tuple[str, ...]] = set()
    # iterative DFS with an explicit path: small graphs, exhaustive walk
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    cycle = tuple(sorted(path))
                    if cycle not in seen_cycles:
                        seen_cycles.add(cycle)
                        out.append(Finding(
                            "lock-order", " -> ".join(path + [start]),
                            "lock acquisition cycle — two threads "
                            "taking these locks in opposite order "
                            "deadlock"))
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))
    return out


# ------------------------------------------------- runtime trace checking

def load_trace_edges(trace_dir: str) -> typing.Set[
        typing.Tuple[str, str]]:
    """Acquisition-order edges observed by ``utils/locks.py`` traced
    runs: every ``lock_trace_*.jsonl`` row carries the lock acquired and
    the locks already held by that thread."""
    edges: typing.Set[typing.Tuple[str, str]] = set()
    for path in sorted(_glob.glob(
            os.path.join(trace_dir, "lock_trace_*.jsonl"))):
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail line of a live writer
                    lock = row.get("lock")
                    for held in row.get("held") or ():
                        if lock and held and held != lock:
                            edges.add((str(held), str(lock)))
        except OSError:
            continue
    return edges


def trace_findings(trace_dir: str) -> typing.List[Finding]:
    """Cycle-check ONLY the observed runtime edges (the static pass
    merges them too; this is the standalone checker for a trace dir)."""
    return order_findings(load_trace_edges(trace_dir))


# ------------------------------------------------------ explorer coupling

def explorer_findings(seeds: typing.Optional[typing.Sequence[int]] = None,
                      edges: typing.Optional[set] = None
                      ) -> typing.List[Finding]:
    """Rule ``interleave``: run the scenario library under permuted
    schedules; every violated invariant is a finding.  Scenario prints
    (membership-change banners etc.) are swallowed — findings are the
    CLI's only output channel."""
    import contextlib
    import io

    from . import interleave

    out = []
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        violations = interleave.run_scenarios(
            seeds=seeds if seeds is not None else interleave.CONC_SEEDS,
            edges=edges)
    for name, seed, message in violations:
        out.append(Finding("interleave", f"{name}@seed{seed}", message))
    return out


# ------------------------------------------------------------- repo entry

def lint_repo_conc(root: str = REPO,
                   subdirs: typing.Sequence[str] = LINT_SUBDIRS,
                   registry: typing.Dict[str, dict] = GUARDED_BY,
                   extra_edges: typing.Iterable[
                       typing.Tuple[str, str]] = (),
                   trace_dir: typing.Optional[str] = None
                   ) -> typing.List[Finding]:
    """Static half of ``--conc``: AST rules over every source file, the
    registry validity check, and the ordering cycle check over static +
    ``extra_edges`` (explorer) + runtime-trace edges."""
    findings: typing.List[Finding] = []
    edges: typing.Set[typing.Tuple[str, str]] = set(extra_edges)
    for path, rel in iter_source_files(root, subdirs):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        file_findings, file_edges = _analyze(rel, source, registry)
        findings.extend(file_findings)
        edges.update(file_edges)
    if trace_dir is None:
        trace_dir = os.environ.get("HBNLP_LOCK_TRACE", "")
    if trace_dir and os.path.isdir(trace_dir):
        edges.update(load_trace_edges(trace_dir))
    findings.extend(registry_findings(root, registry))
    findings.extend(order_findings(edges))
    return findings
