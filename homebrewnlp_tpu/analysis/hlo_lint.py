"""Compiled-HLO audit passes (graft-lint half a).

Every pass takes post-optimization HLO text (``jitted.lower(...).compile()
.as_text()``) plus the caller's expectations and returns ``Finding``s —
nothing raises, so one run can report every violation at once (the CLI and
the tier-1 test decide severity).  The passes generalize
``infer/hlo_check.py`` (which now delegates here):

=====================  ====================================================
pass                   invariant
=====================  ====================================================
donation_audit         every donated leaf appears in ``input_output_alias``
                       — a dropped or unaliasable donation is a silent 2x
                       HBM regression
big_copy_audit         no ``copy``/``copy-done`` produces a buffer shaped
                       like a caller-supplied protected shape (KV caches
                       for decode, param/opt-state leaves for train)
dtype_promotion_audit  no f32 intermediate ``convert``-ed from a bf16
                       buffer shaped like a bf16 param outside an allowlist
                       (an accidental master-weight copy per step)
collective_budget_audit  collective census (all-reduce/all-gather/
                       reduce-scatter/collective-permute/all-to-all) stays
                       within per-entry-point budgets (``budgets.json``) —
                       catches accidental resharding the way the decode
                       scaling test caught cache copies
host_sync_audit        no host callbacks / infeed / outfeed / send / recv
                       on hot paths
=====================  ====================================================

Import is stdlib+numpy only; jax appears nowhere (callers hand us text).
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
import typing

import numpy as np

BUDGETS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "budgets.json")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation: ``rule`` (pass name), ``entry`` (audited entry point
    or source location), human-readable ``message``."""
    rule: str
    entry: str
    message: str

    def __str__(self):
        return f"[{self.rule}] {self.entry}: {self.message}"


# instruction line: "%name = <shape> <op>(...)" — the op name directly
# follows the result shape (post-layout HLO text).  Async pairs: a
# ``copy-start`` result is a TUPLE shape (unmatchable here), but its
# ``copy-done`` twin's result is the plain copied array shape, so matching
# copy-done catches every async copy exactly once.  The same start/done
# convention holds for collectives below.
_COPY_RE = re.compile(
    r"=\s*([a-z0-9]+\[[0-9,]*\])(\{[^}]*\})?\s+copy\("
    r"\s*(?:[a-z0-9]+\[[0-9,]*\])?(\{[^}]*\})?\s*%([a-zA-Z0-9_.-]+)")

# ``copy-done``'s operand is the copy-start TUPLE ``(dest, src, context)``
# — the tuple's first two member layouts are the copy's out/in layouts
_COPY_DONE_RE = re.compile(
    r"=\s*([a-z0-9]+\[[0-9,]*\])(\{[^}]*\})?\s+copy-done\(\s*\(\s*"
    r"[a-z0-9]+\[[0-9,]*\](\{[^}]*\})?\s*,\s*"
    r"[a-z0-9]+\[[0-9,]*\](\{[^}]*\})?[^%]*%([a-zA-Z0-9_.-]+)")

_CONVERT_RE = re.compile(
    r"=\s*f32\[([0-9,]*)\](?:\{[^}]*\})?\s+convert\(\s*bf16\[([0-9,]*)\]")

#: census ops; ``<op>-start`` is counted and ``<op>-done`` ignored so an
#: async pair counts once (a sync ``<op>`` instruction also counts once)
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

_COLLECTIVE_RE = re.compile(
    r"=\s*(?:[a-z0-9]+\[[^\]]*\](?:\{[^}]*\})?|\()[^=]*?\s"
    r"(" + "|".join(COLLECTIVE_OPS) + r")(-start)?\(")

_HOST_OP_RE = re.compile(
    r"=\s*(?:[a-z0-9]+\[[^\]]*\](?:\{[^}]*\})?|\()[^=]*?\s"
    r"(infeed|outfeed|send|recv)(-done)?\(")

#: custom-call targets that round-trip through the host (python callbacks,
#: host transfers) — a per-step host sync on a hot path serializes the
#: device against the GIL
_HOST_CALLBACK_RE = re.compile(
    r'custom-call[^\n]*custom_call_target="([^"]*'
    r'(?:callback|host|py_func|infeed|outfeed)[^"]*)"', re.I)


def input_output_alias_count(hlo_text: str) -> int:
    """Number of entries in the entry module's input_output_alias table."""
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return 0
    # brace-scan to the table's closing brace (entries nest one level:
    # "{0}: (31, {}, may-alias)")
    i = hlo_text.index("{", start)
    depth, end = 0, i
    for end in range(i, len(hlo_text)):
        depth += (hlo_text[end] == "{") - (hlo_text[end] == "}")
        if depth == 0:
            break
    return len(re.findall(r"(?:may|must)-alias", hlo_text[i:end + 1]))


_HLO_DTYPE = {"float32": "f32", "bfloat16": "bf16", "float16": "f16",
              "float64": "f64", "int8": "s8", "uint8": "u8", "int16": "s16",
              "int32": "s32", "int64": "s64", "uint32": "u32",
              "uint64": "u64", "bool": "pred"}


def shape_strings(avals: typing.Mapping[str, typing.Any],
                  key_filter: typing.Optional[str] = None,
                  min_rank: int = 0,
                  dtypes: typing.Optional[typing.Container[str]] = None
                  ) -> typing.Set[str]:
    """HLO shape strings (``f32[2,4,16,2,16]``) of a dict of array-likes
    (anything with ``.shape``/``.dtype``).  ``key_filter`` keeps only names
    containing the substring; ``min_rank`` drops small vectors (norm
    scales) when only matrix-shaped buffers matter; ``dtypes`` restricts to
    the given HLO dtype strings (e.g. ``{"bf16"}``)."""
    out = set()
    for name, v in avals.items():
        if key_filter is not None and key_filter not in name:
            continue
        if len(v.shape) < min_rank:
            continue
        dt = _HLO_DTYPE.get(str(np.dtype(v.dtype)))
        if dt is None or (dtypes is not None and dt not in dtypes):
            continue
        out.add(f"{dt}[{','.join(str(d) for d in v.shape)}]")
    return out


# ---- passes ----------------------------------------------------------------

def donation_audit(entry: str, hlo_text: str, expected_aliases: int
                   ) -> typing.List[Finding]:
    """Donation actually took: at least ``expected_aliases`` entries in the
    input_output_alias table.  Callers pass the donated LEAF count — every
    leaf must alias, a count any cache leaf could miss only by another,
    nonexistent leaf standing in for it."""
    got = input_output_alias_count(hlo_text)
    if got < expected_aliases:
        return [Finding("donation", entry,
                        f"only {got} input_output_alias entries (expected "
                        f">= {expected_aliases}): donated buffers are NOT "
                        "aliased in place — each un-aliased donation is a "
                        "full extra copy of that buffer per call")]
    return []


_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "f64": 8, "s8": 1, "u8": 1,
                "s16": 2, "s32": 4, "s64": 8, "u32": 4, "u64": 8, "pred": 1}


def shape_bytes(shape_string: str) -> int:
    """``"f32[2,16]"`` -> 128."""
    m = re.match(r"([a-z0-9]+)\[([0-9,]*)\]", shape_string)
    if m is None:
        return 0
    n = 1
    for d in m.group(2).split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(m.group(1), 1)


def big_copy_audit(entry: str, hlo_text: str,
                   protected: typing.Set[str],
                   max_copied_bytes: int = 0,
                   max_offenders: int = 8) -> typing.List[Finding]:
    """No ``copy``/``copy-done`` whose result is exactly a protected shape
    (the aliaser inserts such copies when it cannot prove in-place safety).
    Async pairs count once: ``copy-start``'s tuple result is unmatchable,
    its ``copy-done`` twin carries the copied array shape — at production
    scale XLA emits exactly the big copies this pass polices as async
    pairs, so missing them would blind the audit where it matters most.

    Three copy flavors are legitimate and skipped: differently-shaped
    buffers (row-sized scatter traffic, block-sized slices), copies of a
    fresh ``broadcast``/``constant``/``iota`` result (materializing an
    init value into a loop carry — one write that has to happen anyway,
    not a duplication of live state), and RELAYOUT copies of an explicit
    data-movement result (``transpose``/``bitcast``/``reshape`` operand —
    layout assignment materializing an intermediate the math asked for;
    the train step's optimizer transposes land here).  A relayout copy of
    LIVE state (``get-tuple-element``/parameter operand) is NOT exempt:
    an unaliasable cache layout reintroduces the per-token multi-GB copy
    (the pre-refactor decode checker named it a failure), so it counts
    toward the byte budget like any other full-buffer copy.

    ``max_copied_bytes``: tolerated total bytes of such copies.  0 (the
    decode default) flags ANY protected copy; the train step runs with a
    small fraction of its donated bytes (budgets.json
    ``copy_byte_fraction``) because XLA legitimately preserves a
    multiply-consumed small leaf (e.g. an embedding table read by forward
    AND subtracted by the update) — the failure mode is the dominant
    leaves copying, which blows any small fraction immediately."""
    if not protected:
        return []
    offenders, copied = [], 0
    for line in hlo_text.splitlines():
        m = _COPY_RE.search(line)
        if m is not None:
            shape, out_layout, in_layout, operand = m.groups()
        else:
            m = _COPY_DONE_RE.search(line)
            if m is None:
                continue
            shape, _, out_layout, in_layout, operand = m.groups()
        if shape not in protected:
            continue
        op_kind = operand.split(".")[0]
        if op_kind in ("broadcast", "constant", "iota"):
            continue  # fresh init value, not duplicated live state
        if (out_layout and in_layout and out_layout != in_layout
                and op_kind in ("transpose", "bitcast", "reshape")):
            continue  # layout assignment materializing an intermediate
        copied += shape_bytes(shape)
        offenders.append(line.strip())
    if offenders and copied > max_copied_bytes:
        return [Finding("big-copy", entry,
                        f"{len(offenders)} full-buffer copy(s) of protected "
                        f"shapes ({copied} bytes copied, budget "
                        f"{max_copied_bytes}) — the update is NOT aliased "
                        "in place:\n"
                        + "\n".join(offenders[:max_offenders]))]
    return []


def dtype_promotion_audit(entry: str, hlo_text: str,
                          bf16_param_shapes: typing.Set[str],
                          allow: typing.Collection[str] = ()
                          ) -> typing.List[Finding]:
    """No ``f32[dims] convert(bf16[dims])`` where ``dims`` matches a bf16
    param shape outside ``allow`` — a param-shaped f32 intermediate is an
    accidental master-weight copy materialized every step.  Shapes are
    dims-only strings (``"512,512"``); pass param leaves through
    ``shape_strings(..., dtypes={"bf16"})`` and strip the dtype prefix with
    ``dims_of``."""
    if not bf16_param_shapes:
        return []
    dims_set = {dims_of(s) for s in bf16_param_shapes}
    allow_set = {dims_of(s) for s in allow}
    offenders = []
    for line in hlo_text.splitlines():
        m = _CONVERT_RE.search(line)
        if m is None:
            continue
        out_dims, in_dims = m.group(1), m.group(2)
        if (out_dims == in_dims and out_dims in dims_set
                and out_dims not in allow_set):
            offenders.append(line.strip())
    if offenders:
        return [Finding("dtype-promotion", entry,
                        f"{len(offenders)} f32 intermediate(s) converted "
                        "from bf16-param-shaped buffers (accidental "
                        "master-weight promotion):\n"
                        + "\n".join(offenders[:8]))]
    return []


_INT8_CONVERT_RE = re.compile(
    r"=\s*(?:f32|bf16|f16)\[[0-9,]*\](?:\{[^}]*\})?\s+convert\(\s*"
    r"s8\[[0-9,]*\]")


def int8_promotion_audit(entry: str, hlo_text: str,
                         scopes: typing.Collection[str] = ("dequant",
                                                           "cache_read")
                         ) -> typing.List[Finding]:
    """Every float ``convert`` of an int8 operand must belong to a named
    dequant scope.

    The quantized paths promise int8 reaches float exactly once, inside a
    named fused-dequant region: weights (``serve_quantized_weights``,
    ``train_quantized_matmuls``) under ``named_scope("dequant")``
    (``core.scope.materialize_param`` / ``core.quant.ste_dequantize``),
    and int8 KV caches (``decode_cache_dtype: "int8"``) under the decode
    path's ``named_scope("cache_read")`` (model/decode.py) — both are
    allowed by default.  Any OTHER s8 -> float convert is an accidental
    full-precision materialization of a quantized buffer: it silently
    costs the float copy's HBM and hides the bandwidth saving the knobs
    exist for.  An instruction qualifies when its ``op_name`` metadata
    path contains one of ``scopes``."""
    offenders = []
    for line in hlo_text.splitlines():
        if _INT8_CONVERT_RE.search(line) is None:
            continue
        op = _OP_NAME_IN_LINE_RE.search(line)
        path = op.group(1) if op else ""
        if not any(s in path for s in scopes):
            offenders.append(line.strip())
    if offenders:
        return [Finding("int8-promotion", entry,
                        f"{len(offenders)} float convert(s) of int8 "
                        "operands outside the fused dequant scope "
                        "(quantized weights silently re-materialized in "
                        "full precision):\n" + "\n".join(offenders[:8]))]
    return []


_OP_NAME_IN_LINE_RE = re.compile(r'op_name="([^"]+)"')


def dims_of(shape_string: str) -> str:
    """``"bf16[512,512]"`` -> ``"512,512"`` (idempotent on bare dims)."""
    m = re.search(r"\[([0-9,]*)\]", shape_string)
    return m.group(1) if m else shape_string


def collective_census(hlo_text: str) -> typing.Dict[str, int]:
    """Count of each collective op in the module (async pairs once)."""
    census = {op: 0 for op in COLLECTIVE_OPS}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        census[m.group(1)] += 1
    return census


#: one instruction line carrying a collective: the full result segment
#: (between '=' and the op name) is captured for byte accounting
_COLLECTIVE_LINE_RE = re.compile(
    r"=\s*([^=]*?)\s(" + "|".join(COLLECTIVE_OPS) + r")(-start|-done)?\(")

_SHAPE_TOKEN_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")

_REPLICA_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[^}]*(?:\},\{[^}]*)*\}\}|\[[0-9,]+\]<=\[[0-9,]+\]"
    r"(?:T\([0-9,]+\))?)")
_SOURCE_TARGET_RE = re.compile(r"source_target_pairs=\{([^}]*(?:\},\{[^}]*)*)\}")


def _parse_replica_groups(spec: str) -> typing.List[typing.List[int]]:
    """Both HLO spellings -> explicit groups.

    ``{{0,2},{1,3}}`` (explicit) and the iota form ``[2,4]<=[8]`` /
    ``[2,4]<=[4,2]T(1,0)`` (groups = transpose(reshape(arange(N), dims),
    perm).reshape(G, S))."""
    if spec.startswith("{"):
        return [[int(x) for x in grp.split(",") if x.strip() != ""]
                for grp in re.findall(r"\{([0-9,\s]*)\}", spec) if grp.strip()]
    m = re.match(r"\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?", spec)
    if m is None:
        return []
    gshape = [int(x) for x in m.group(1).split(",")]
    rdims = [int(x) for x in m.group(2).split(",")]
    ids = np.arange(int(np.prod(rdims))).reshape(rdims)
    if m.group(3):
        ids = ids.transpose([int(x) for x in m.group(3).split(",")])
    return ids.reshape(gshape).tolist()


def group_axes(groups: typing.Sequence[typing.Sequence[int]],
               mesh_shape: typing.Mapping[str, int]) -> typing.Tuple[str, ...]:
    """Which mesh axes a replica-group set communicates over.

    Device/partition ids are positions in the mesh's device array flattened
    in axis order (how jax assigns logical ids), so ``unravel_index`` maps
    each member to mesh coordinates; an axis the members DIFFER on is an
    axis the collective moves data across.  ``mesh_shape`` must be the
    ordered axis -> size mapping of the audited mesh."""
    axes = list(mesh_shape)
    sizes = [mesh_shape[a] for a in axes]
    varying: typing.Set[str] = set()
    for grp in groups:
        if len(grp) < 2:
            continue
        coords = np.asarray([np.unravel_index(i, sizes) for i in grp])
        for k, a in enumerate(axes):
            if len(set(coords[:, k].tolist())) > 1:
                varying.add(a)
    return tuple(a for a in axes if a in varying)


def _pairs_axes(pairs_text: str, mesh_shape: typing.Mapping[str, int]
                ) -> typing.Tuple[str, ...]:
    """Axes of a ``source_target_pairs`` permute (each pair one group)."""
    pairs = re.findall(r"\{?\s*(\d+)\s*,\s*(\d+)\s*\}?", pairs_text)
    return group_axes([[int(a), int(b)] for a, b in pairs], mesh_shape)


def _result_bytes(result_segment: str, async_start: bool) -> int:
    """Bytes of a collective's result shapes.

    Sync ops: sum every array in the (possibly tuple) result — variadic
    all-reduces list one shape per operand.  Async ``-start`` tuples
    interleave operand and result aliases ``(in..., out..., ctx)``; summing
    would double-count, so take the LARGEST array (equals the shape for
    all-reduce, the gathered output for all-gather)."""
    sizes = [int(np.prod([int(d) for d in dims.split(",") if d]))
             * _DTYPE_BYTES.get(dt, 1)
             for dt, dims in _SHAPE_TOKEN_RE.findall(result_segment)]
    if not sizes:
        return 0
    return max(sizes) if async_start else sum(sizes)


def collective_inventory(hlo_text: str,
                         mesh_shape: typing.Optional[
                             typing.Mapping[str, int]] = None
                         ) -> typing.Dict[str, dict]:
    """Per-kind ``{"count", "bytes"[, "axes"]}`` census of one compiled
    module — the ONE census shared by ``scripts/pod_lowering.py`` reports,
    the dryrun MULTICHIP rows, and the mesh-budget audit, so they can never
    disagree on a count.  Counting conventions match
    :func:`collective_census` exactly (sync once, async pairs once via the
    ``-start`` twin; ``-done`` ignored).

    ``bytes``: result-shape bytes per :func:`_result_bytes` — a consistent
    *metric*, not a wire model (an all-gather's result counts the gathered
    array once; per-link traffic differs per algorithm).

    With ``mesh_shape`` (ordered axis -> size of the audited mesh) each
    kind also carries ``"axes"``: counts keyed by the ``+``-joined mesh
    axes its replica groups / permute pairs span — the attribution that
    lets a budget failure NAME the axis a surplus collective reshards
    over."""
    inv: typing.Dict[str, dict] = {}
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_LINE_RE.search(line)
        if m is None:
            continue
        result_seg, kind, suffix = m.groups()
        if suffix == "-done":
            continue
        entry = inv.setdefault(kind, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += _result_bytes(result_seg, suffix == "-start")
        if mesh_shape is None:
            continue
        axes: typing.Tuple[str, ...] = ()
        g = _REPLICA_GROUPS_RE.search(line)
        if g is not None:
            axes = group_axes(_parse_replica_groups(g.group(1)), mesh_shape)
        else:
            p = _SOURCE_TARGET_RE.search(line)
            if p is not None:
                axes = _pairs_axes(p.group(1), mesh_shape)
        key = "+".join(axes) if axes else "none"
        per_axes = entry.setdefault("axes", {})
        per_axes[key] = per_axes.get(key, 0) + 1
    return inv


def collective_budget_audit(entry: str,
                            census: typing.Mapping[str, int],
                            budget: typing.Mapping[str, int]
                            ) -> typing.List[Finding]:
    """Census within budget; an op missing from the budget is budget 0 (a
    NEW collective kind appearing is exactly the regression this catches)."""
    findings = []
    for op, n in sorted(census.items()):
        cap = int(budget.get(op, 0))
        if n > cap:
            findings.append(Finding(
                "collective-budget", entry,
                f"{n} x {op} (budget {cap}) — an unbudgeted collective "
                "usually means accidental resharding; if the comms are "
                "intentional, raise the budget in analysis/budgets.json "
                "with a PR note"))
    return findings


def host_sync_audit(entry: str, hlo_text: str) -> typing.List[Finding]:
    """No host round-trips compiled into the module: infeed/outfeed/send/
    recv ops or callback-flavored custom-call targets."""
    offenders = []
    for line in hlo_text.splitlines():
        m = _HOST_OP_RE.search(line)
        if m is not None and m.group(2) is None:  # count start/sync once
            offenders.append(f"{m.group(1)}: {line.strip()[:120]}")
            continue
        c = _HOST_CALLBACK_RE.search(line)
        if c is not None:
            offenders.append(f"custom-call {c.group(1)}: "
                             f"{line.strip()[:120]}")
    if offenders:
        return [Finding("host-sync", entry,
                        f"{len(offenders)} host-sync op(s) compiled into a "
                        "hot path:\n" + "\n".join(offenders[:8]))]
    return []


# ---- budgets + one-call audit ---------------------------------------------

def load_budgets(path: typing.Optional[str] = None) -> dict:
    with open(path or BUDGETS_PATH) as f:
        return json.load(f)


def audit(entry: str, hlo_text: str, *,
          expected_aliases: typing.Optional[int] = None,
          protected_shapes: typing.Optional[typing.Set[str]] = None,
          max_copied_bytes: int = 0,
          bf16_param_shapes: typing.Optional[typing.Set[str]] = None,
          promotion_allow: typing.Collection[str] = (),
          budget: typing.Optional[typing.Mapping[str, int]] = None,
          check_host_sync: bool = True) -> typing.List[Finding]:
    """Run every applicable pass over one compiled module.  ``None``
    disables a pass (the caller knows which invariants its entry point
    promises); the budget defaults to all-zero when a mapping is given."""
    findings: typing.List[Finding] = []
    if expected_aliases is not None:
        findings += donation_audit(entry, hlo_text, expected_aliases)
    if protected_shapes:
        findings += big_copy_audit(entry, hlo_text, protected_shapes,
                                   max_copied_bytes)
    if bf16_param_shapes:
        findings += dtype_promotion_audit(entry, hlo_text, bf16_param_shapes,
                                          promotion_allow)
    if budget is not None:
        findings += collective_budget_audit(
            entry, collective_census(hlo_text), budget)
    if check_host_sync:
        findings += host_sync_audit(entry, hlo_text)
    # always on: vacuously clean on int8-free modules, and the quantized
    # paths (serve_quantized_weights / train_quantized_matmuls) get their
    # no-promotion-outside-dequant invariant audited for free the moment
    # an entry point compiles with int8 weights
    findings += int8_promotion_audit(entry, hlo_text)
    return findings
