"""Mesh-aware graft-lint: sharding contracts, per-mesh collective budgets,
and HBM liveness audits for every parallel strategy
(docs/STATIC_ANALYSIS.md 'Mesh audit').

The single-device HLO audit (``entry_points.py`` + ``hlo_lint.py``) pins
"zero collectives" — the one regression it CANNOT catch is the one that
matters at pod scale: an accidental resharding or full-gather under a real
parallel strategy, exactly what the Mesh-TF layout claim (PAPERS.md
1811.02084) and the pjit-TPUv4 scaling analysis (2204.06514) attribute
most lost scaling to.  This module lowers the registered entry points
under each ``scripts/pod_lowering.py`` / dryrun strategy on 8 virtual CPU
devices and audits the compiled per-mesh HLO against three contracts:

1. **collective budgets** — measured count AND result-bytes per collective
   kind, committed under the ``meshes`` section of ``budgets.json``
   (tolerance-checked like ``cost_ledger.json``; regenerated via
   ``python -m homebrewnlp_tpu.analysis.mesh_audit --write``).  Replica
   groups are mapped back to mesh coordinates, so a failure NAMES the mesh
   axis the surplus collective reshards over.  An analytic floor per
   strategy (mesh shape x model dims: grad reduction bytes over 'data',
   ring hops over 'sequence', tp partials over 'model') gates ``--write``
   so a degenerate baseline (strategy silently not parallel, or already
   resharded) cannot be committed as the budget.
2. **sharding specs** — protected param / activation-input / KV-cache
   leaves must appear in the compiled module's ENTRY parameters at their
   strategy-contracted shard shapes (the contract is declared HERE, per
   strategy, independent of ``config.layout`` — a broken layout rule fails
   the audit instead of silently replicating).  Silent full replication
   and compiler-inserted all-gathers of model-parallel leaves are findings.
3. **HBM liveness** — per entry x mesh, a buffer-level walk of the
   compiled text (donated arguments stay live; temporaries alloc at
   definition, free at last use; called computations contribute their own
   internal peak at the call site) yields a per-chip peak-bytes estimate,
   budget-checked against the committed value AND the target chip's HBM —
   an OOM-at-32-chips regression fails CI on this CPU-only box.

Environment gaps are classified, not papered over: jax 0.4.37 cannot
compile the pipeline schedules' partial-manual ``axis_index``
("PartitionId ... not supported"), so those strategies carry a
``pending`` budget row and are skipped LOUDLY until an environment that
lowers them regenerates their budgets.

jax is imported inside functions only (package convention — the AST-only
consumers must import cheaply).
"""
from __future__ import annotations

import dataclasses
import json
import re
import typing

import numpy as np

from . import entry_points, hlo_lint
from .hlo_lint import Finding

#: every mesh strategy lowers on this many virtual CPU devices — the same
#: count tests/conftest.py forces, and enough for 3-axis meshes
MESH_DEVICES = 8

#: relative drift in committed counts / bytes the audit tolerates
DEFAULT_TOLERANCE = 0.10

#: substrings identifying a lowering failure as an ENVIRONMENT gap (the
#: strategy is skipped with a notice) rather than a repo regression.
#: Deliberately NARROW: only the old-XLA partial-manual axis_index gap
#: qualifies — a TypeError/AttributeError around shard_map now means a
#: call site bypassed ``parallel/compat.py`` (a repo bug that must FAIL,
#: not skip; the compat shim translates every legitimate spelling)
_ENV_GAP_MARKERS = (
    "PartitionId instruction is not supported",
)


@dataclasses.dataclass(frozen=True)
class MeshStrategy:
    """One parallel strategy the audit lowers and budgets.

    ``overrides``: audit-config overrides (mesh_shape_override and the
    blocks that exercise the strategy), mirroring the dryrun legs
    (``__graft_entry__.dryrun_multichip``) at ``AUDIT_CONFIG`` scale.
    ``entries``: which registered entry points lower under it (train
    everywhere; decode/engine only where serving runs the strategy).
    ``sharded_dims``: the sharding CONTRACT — named model dims that must
    shard over the given mesh axis (declared here, independent of the
    config's layout rules, so a layout regression is caught).
    ``collective_axes``: mesh axes collectives may legitimately span;
    a censused group over any other axis refuses ``--write``.
    ``hbm_device``: chip whose HBM bounds the liveness estimate.
    """
    name: str
    overrides: typing.Mapping[str, typing.Any]
    entries: typing.Tuple[str, ...] = ("train_step",)
    sharded_dims: typing.Mapping[str, str] = dataclasses.field(
        default_factory=dict)
    collective_axes: typing.FrozenSet[str] = frozenset()
    hbm_device: str = "TPU v5e"
    description: str = ""


_RING_BLOCKS = [{"layer": ["norm-shift-scale-features-group",
                           "attention-dot_product-context"]}]
_MOE_BLOCKS = [{"layer": ["norm-shift-scale-features-group",
                          "feed_forward-in:relu-in:mixture_of_experts"
                          "-in:routed"]}]

#: the registry: keys are budgets.json ``meshes`` keys; meshes mirror the
#: MULTICHIP dryrun legs (dp x tp, ring-attention SP, routed MoE EP, and
#: the three pipeline schedules) at audit scale on 8 devices
MESH_STRATEGIES: typing.Dict[str, MeshStrategy] = {
    "dp_tp": MeshStrategy(
        "dp_tp",
        {"mesh_shape_override": {"data": 4, "model": 2}},
        entries=("train_step", "train_step_bucketed", "decode_chunk_step",
                 "engine_chunk_step", "spec_chunk_step", "paged_chunk_step",
                 "spec_paged_chunk_step"),
        sharded_dims={"heads": "model"},
        collective_axes=frozenset({"data", "model"}),
        description="2-D data x tensor parallelism (heads over 'model')"),
    "ring_sp": MeshStrategy(
        "ring_sp",
        {"mesh_shape_override": {"data": 2, "sequence": 4},
         "block_config": _RING_BLOCKS},
        sharded_dims={},  # params replicate; the sequence activations shard
        collective_axes=frozenset({"data", "sequence"}),
        description="ring-attention sequence parallelism (zigzag ring)"),
    "moe_ep": MeshStrategy(
        "moe_ep",
        {"mesh_shape_override": {"data": 4, "model": 2},
         "block_config": _MOE_BLOCKS, "experts": 4, "moe_top_k": 2,
         "moe_capacity_factor": 2.0,
         "layout_override": {"experts": "model", "heads": None}},
        sharded_dims={"experts": "model"},
        collective_axes=frozenset({"data", "model"}),
        description="routed top-k MoE expert parallelism (experts over "
                    "'model')"),
    "pp_gpipe": MeshStrategy(
        "pp_gpipe",
        {"mesh_shape_override": {"data": 2, "pipe": 2, "model": 2},
         "train_batch_size": 8},
        sharded_dims={"heads": "model"},
        collective_axes=frozenset({"data", "pipe", "model"}),
        description="GPipe microbatch pipeline + tensor parallelism"),
    "pp_1f1b": MeshStrategy(
        "pp_1f1b",
        {"mesh_shape_override": {"data": 2, "pipe": 2, "model": 2},
         "train_batch_size": 8, "pipeline_schedule": "1f1b",
         "pipeline_microbatches": 4},
        sharded_dims={"heads": "model"},
        collective_axes=frozenset({"data", "pipe", "model"}),
        description="1F1B pipeline schedule + tensor parallelism"),
    "pp_interleaved": MeshStrategy(
        "pp_interleaved",
        {"mesh_shape_override": {"data": 2, "pipe": 2, "model": 2},
         "train_batch_size": 8, "depth": 4, "pipeline_schedule": "1f1b",
         "pipeline_interleave": 2, "pipeline_microbatches": 2},
        sharded_dims={"heads": "model"},
        collective_axes=frozenset({"data", "pipe", "model"}),
        description="interleaved 1F1B (V=2 virtual stages) + tp"),
}


# ---- shared aval lowering (scripts/pod_lowering.py delegates here) ---------

def cheap_init_patch():
    """Replace the numpy QR/normal initializers with zeros for an
    aval-only lowering (AOT consumes shapes/dtypes/shardings; QR of big
    matrices is minutes of host time buying nothing).  Returns undo()."""
    from ..model import backend

    saved = (backend.OrthogonalInit.__call__, backend.NormalInit.__call__)

    def zeros_init(self, rng, sizes):
        return np.zeros(sizes, np.float32)

    backend.OrthogonalInit.__call__ = zeros_init
    backend.NormalInit.__call__ = zeros_init

    def undo():
        backend.OrthogonalInit.__call__, backend.NormalInit.__call__ = saved

    return undo


def opt_state_avals(optimizer, var_avals, mesh):
    """Optimizer slot avals via the REAL ``Optimizer.init`` slot
    discovery, with materialisation swapped for ShapeDtypeStructs
    (``_zeros_for``'s sharding rule: same-shape slots inherit the
    variable's sharding, reduced-shape slots replicate)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from .. import optim as optim_mod

    saved = optim_mod._zeros_for

    def aval_zeros(variable, shape, dtype):
        sharding = getattr(variable, "sharding", None)
        if sharding is None or tuple(shape) != tuple(variable.shape):
            sharding = NamedSharding(mesh, PartitionSpec())
        return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sharding)

    optim_mod._zeros_for = aval_zeros
    try:
        return optimizer.init(var_avals)
    finally:
        optim_mod._zeros_for = saved


def train_step_avals(params, model, mesh, cheap_init: bool = True):
    """``(state_avals, batch_avals, rng_aval, info)`` for lowering the
    donated train step without materialising anything on devices — the ONE
    aval-construction path shared by the mesh audit and
    ``scripts/pod_lowering.py`` (which used to carry its own copy)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from .. import optim as optim_mod
    from ..core import sharding as shardlib
    from ..train import TrainState

    seq = params.sequence_length // params.token_patch_size
    batch_np = {
        "token_x": np.zeros((params.train_batch_size, seq,
                             params.token_patch_size), np.int32),
        "token_y": np.zeros((params.train_batch_size, seq,
                             params.token_patch_size), np.int32)}
    undo = cheap_init_patch() if cheap_init else (lambda: None)
    try:
        variables = model.init(batch_np)
    finally:
        undo()
    var_avals = {
        k: jax.ShapeDtypeStruct(
            np.shape(v), np.asarray(v).dtype,
            sharding=shardlib.named_sharding(
                params, model.param_dims.get(k, ()), mesh))
        for k, v in variables.items()}
    n_params = sum(int(np.prod(a.shape)) for a in var_avals.values())
    del variables  # free the host zeros before compiling

    optimizer = optim_mod.Optimizer(params, model.param_dims)
    opt_avals = opt_state_avals(optimizer, var_avals, mesh)
    repl = NamedSharding(mesh, PartitionSpec())
    state_avals = TrainState(
        var_avals, opt_avals,
        jax.ShapeDtypeStruct((), np.int32, sharding=repl))

    batch_entries: typing.List[typing.Optional[str]] = [None] * 3
    if params.train_batch_size % mesh.shape.get(shardlib.DATA_AXIS, 1) == 0:
        batch_entries[0] = shardlib.DATA_AXIS
    batch_sharding = NamedSharding(mesh, PartitionSpec(*batch_entries))
    batch_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype,
                                           sharding=batch_sharding)
                   for k, v in batch_np.items()}
    rng_aval = jax.ShapeDtypeStruct((2,), np.uint32, sharding=repl)
    info = {"n_params": n_params, "var_avals": var_avals,
            "optimizer": optimizer}
    return state_avals, batch_avals, rng_aval, info


# ---- strategy lowering ------------------------------------------------------

def audit_devices(n: int = MESH_DEVICES):
    """First ``n`` jax devices; raises with the bootstrap hint when the
    process has fewer (scripts/graft_lint.py re-runs the mesh half in a
    CPU-virtual subprocess in that case)."""
    import jax

    devices = jax.devices()
    if len(devices) < n:
        raise RuntimeError(
            f"mesh audit needs {n} devices, have {len(devices)} — run "
            f"under JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_"
            f"device_count={n} (scripts/graft_lint.py --mesh does this "
            f"automatically)")
    return devices[:n]


def classify_env_gap(exc: BaseException) -> typing.Optional[str]:
    """Non-None (the marker) when a lowering failure is a known gap of the
    CURRENT jax/XLA, not a repo regression."""
    text = f"{type(exc).__name__}: {exc}"
    for marker in _ENV_GAP_MARKERS:
        if marker in text:
            return marker
    return None


def _strategy_params_model(strategy: MeshStrategy):
    from ..config import ModelParameter
    from ..model import Model

    cfg = dict(entry_points.AUDIT_CONFIG)
    cfg.update(tpu_size=MESH_DEVICES, model_path="/tmp/mesh_audit")
    cfg.update(strategy.overrides)
    params = ModelParameter(cfg)
    return params, Model(params)


def expected_shard_shape(shape: typing.Sequence[int], dims, contract,
                         mesh_shape) -> typing.Tuple[int, ...]:
    """Per-chip shape the strategy contract demands for a leaf with named
    ``dims`` (each mesh axis used at most once, divisibility respected —
    the same visible rules as ``shardlib.spec_for_dims``, but driven by
    the strategy's OWN contract so the two can disagree and fail)."""
    out = list(shape)
    used: typing.Set[str] = set()
    for i, d in enumerate(dims):
        axis = contract.get(getattr(d, "name", None))
        if (axis is not None and axis in mesh_shape and axis not in used
                and out[i] % mesh_shape[axis] == 0):
            out[i] //= mesh_shape[axis]
            used.add(axis)
    return tuple(out)


def _shape_str(dtype, shape) -> str:
    dt = hlo_lint._HLO_DTYPE.get(str(np.dtype(dtype)), str(dtype))
    return f"{dt}[{','.join(str(int(d)) for d in shape)}]"


def _train_protected(params, model, var_avals, strategy, mesh
                     ) -> typing.Dict[str, dict]:
    """Protected-leaf table for the sharding-spec audit: model-parallel
    params (contract-sharded dims) + the batch inputs (data-sharded
    leading dim)."""
    from ..core import sharding as shardlib

    protected: typing.Dict[str, dict] = {}
    for name, aval in var_avals.items():
        dims = model.param_dims.get(name, ())
        exp = expected_shard_shape(aval.shape, dims, strategy.sharded_dims,
                                   mesh.shape)
        if tuple(exp) == tuple(aval.shape):
            continue  # contract leaves it unsharded — nothing to pin
        protected[name] = {
            "kind": "exact",
            "full": _shape_str(aval.dtype, aval.shape),
            "shard": _shape_str(aval.dtype, exp),
            "axes": sorted(set(strategy.sharded_dims.values()))}
    data = mesh.shape.get(shardlib.DATA_AXIS, 1)
    if data > 1 and params.train_batch_size % data == 0:
        seq = params.sequence_length // params.token_patch_size
        full = (params.train_batch_size, seq, params.token_patch_size)
        shard = (params.train_batch_size // data,) + full[1:]
        for key in ("token_x", "token_y"):
            protected[key] = {
                "kind": "exact",
                "full": _shape_str(np.int32, full),
                "shard": _shape_str(np.int32, shard),
                "axes": [shardlib.DATA_AXIS]}
    return protected


def _cache_protected(cache_shapes: typing.Mapping[str, typing.Any]
                     ) -> typing.Dict[str, dict]:
    """KV-cache leaves: the contract is "NOT fully replicated" — a cache
    materialised at its full shape on every chip is the 8x-HBM serving
    regression; which dims shard (batch over 'data', heads over 'model')
    is the compiler's choice the census already pins."""
    return {name: {"kind": "sharded_any",
                   "full": _shape_str(v.dtype, v.shape)}
            for name, v in cache_shapes.items()}


def lower_train_under_mesh(strategy: MeshStrategy, devices=None,
                           bucketed: bool = False):
    """``(hlo_text, context)`` of the donated train step compiled under
    the strategy's mesh from avals.  ``bucketed`` audits the SAME step
    with ``grad_allreduce="bucketed"`` (the overlap-aware per-bucket
    gradient reduction, budgets key ``train_step_bucketed``) so the two
    collective schedules are both regression-pinned."""
    from ..core import sharding as shardlib
    from ..train import Trainer

    if bucketed:
        strategy = dataclasses.replace(
            strategy, overrides={**dict(strategy.overrides),
                                 "grad_allreduce": "bucketed"})
    params, model = _strategy_params_model(strategy)
    devices = audit_devices() if devices is None else devices
    mesh = shardlib.build_mesh(params, devices)
    state_avals, batch_avals, rng_aval, info = train_step_avals(
        params, model, mesh, cheap_init=False)
    trainer = Trainer(params, model, mesh)
    trainer.optimizer = info["optimizer"]
    compiled = trainer._build_step().lower(
        state_avals, batch_avals, rng_aval).compile()
    hlo = compiled.as_text()
    context = {
        "mesh_shape": dict(mesh.shape),
        "protected": _train_protected(params, model, info["var_avals"],
                                      strategy, mesh),
        "param_bytes": sum(a.size * a.dtype.itemsize
                           for a in info["var_avals"].values()),
        "compiled": compiled,
    }
    return hlo, context


def lower_serving_under_mesh(strategy: MeshStrategy, entry: str,
                             devices=None):
    """``(hlo_text, context)`` of ``decode_chunk_step`` /
    ``engine_chunk_step`` compiled under the strategy's INFERENCE mesh
    (``shardlib.inference_mesh`` — 'pipe'/'sequence' folded into 'data'),
    reusing the registered entry-point lowerings so serving audits the
    exact production program shape."""
    import jax
    import jax.numpy as jnp

    from ..core import sharding as shardlib

    params, model = _strategy_params_model(strategy)
    devices = audit_devices() if devices is None else devices
    mesh = shardlib.inference_mesh(params, devices)
    seq = params.sequence_length // params.token_patch_size
    batch_np = {"token_x": np.zeros((params.train_batch_size, seq,
                                     params.token_patch_size), np.int32),
                "token_y": np.zeros((params.train_batch_size, seq,
                                     params.token_patch_size), np.int32)}
    variables = model.init(batch_np)
    var_avals = {
        k: jax.ShapeDtypeStruct(
            np.shape(v), np.asarray(v).dtype,
            sharding=shardlib.named_sharding(
                params, model.param_dims.get(k, ()), mesh))
        for k, v in variables.items()}
    tok = jnp.zeros(batch_np["token_x"].shape, jnp.int32)
    if entry == "decode_chunk_step":
        hlo, ctx = entry_points.lower_decode_step(model, var_avals, tok,
                                                  mesh=mesh)
    elif entry == "engine_chunk_step":
        hlo, ctx = entry_points.lower_engine_step(model, var_avals, tok,
                                                  mesh=mesh)
    elif entry == "paged_chunk_step":
        # the paged pools inherit the KV layout constraints through the
        # same _constrain_cache path as the slot pool (the views are
        # constrained in-loop; the pools are their storage), so the audit
        # covers the sharded serving shape of the paged program
        hlo, ctx = entry_points.lower_paged_step(model, var_avals, tok,
                                                 mesh=mesh)
    elif entry in ("spec_chunk_step", "spec_paged_chunk_step"):
        # the draft rides the same strategy at DRAFT_AUDIT_OVERRIDES width;
        # its param avals carry the same layout-rule shardings as the
        # target's, so the compiled program shards the draft pool too (the
        # sharding CONTRACT below stays on the target's leaves — the two
        # models' param names collide, and the target pool is the one whose
        # full-replication would be the 8x-HBM regression)
        dstrategy = dataclasses.replace(
            strategy, overrides={**dict(strategy.overrides),
                                 **entry_points.DRAFT_AUDIT_OVERRIDES})
        dparams, dmodel = _strategy_params_model(dstrategy)
        dvariables = dmodel.init(batch_np)
        dvar_avals = {
            k: jax.ShapeDtypeStruct(
                np.shape(v), np.asarray(v).dtype,
                sharding=shardlib.named_sharding(
                    dparams, dmodel.param_dims.get(k, ()), mesh))
            for k, v in dvariables.items()}
        lower = (entry_points.lower_spec_step if entry == "spec_chunk_step"
                 else entry_points.lower_spec_paged_step)
        hlo, ctx = lower(model, var_avals, tok, draft_model=dmodel,
                         draft_variables=dvar_avals, mesh=mesh)
        # two models in one program share every leaf NAME (same scope paths
        # at two widths), so the by-name metadata join cannot tell target
        # from draft parameters: the spec entry keeps the cache-pool
        # sharded_any contract (a full-shape pool replication is the HBM
        # regression this pass exists for) and leaves the exact per-param
        # contract to engine_chunk_step, which audits the identical target
        # params under the identical layout without the collision
        protected = _cache_protected(
            {k: v for k, v in ctx["cache_shapes"].items()
             if not k.startswith("draft/")})
        return hlo, {"mesh_shape": dict(mesh.shape), "protected": protected,
                     "param_bytes": sum(a.size * a.dtype.itemsize
                                        for a in var_avals.values()),
                     "compiled": ctx["compiled"]}
    else:
        raise ValueError(f"unsupported serving entry {entry!r}")
    protected = _cache_protected(ctx["cache_shapes"])
    # model-parallel param leaves keep the training contract at serve time
    for name, aval in var_avals.items():
        dims = model.param_dims.get(name, ())
        exp = expected_shard_shape(aval.shape, dims, strategy.sharded_dims,
                                   mesh.shape)
        if tuple(exp) != tuple(aval.shape):
            protected[name] = {
                "kind": "exact",
                "full": _shape_str(aval.dtype, aval.shape),
                "shard": _shape_str(aval.dtype, exp),
                "axes": sorted(set(strategy.sharded_dims.values()))}
    context = {
        "mesh_shape": dict(mesh.shape),
        "protected": protected,
        "param_bytes": sum(a.size * a.dtype.itemsize
                           for a in var_avals.values()),
        "compiled": ctx["compiled"],
    }
    return hlo, context


def lower_strategy(strategy: MeshStrategy, devices=None
                   ) -> typing.Tuple[typing.Dict[str, typing.Tuple[str, dict]],
                                     typing.Dict[str, str]]:
    """``({entry: (hlo, ctx)}, {entry: env_gap_reason})`` for one
    strategy — entries that lower are KEPT even when a later entry hits
    an environment gap (a dp_tp train audit must not vanish because the
    engine entry gapped); any non-gap exception propagates."""
    out: typing.Dict[str, typing.Tuple[str, dict]] = {}
    gaps: typing.Dict[str, str] = {}
    for entry in strategy.entries:
        try:
            if entry == "train_step":
                out[entry] = lower_train_under_mesh(strategy, devices)
            elif entry == "train_step_bucketed":
                out[entry] = lower_train_under_mesh(strategy, devices,
                                                    bucketed=True)
            else:
                out[entry] = lower_serving_under_mesh(strategy, entry,
                                                      devices)
        except Exception as exc:  # noqa: BLE001 — classified below
            reason = classify_env_gap(exc)
            if reason is None:
                raise
            gaps[entry] = reason
    return out, gaps


def lower_strategies(devices=None, strategies=None):
    """``({strategy: {entry: (hlo, ctx)}}, skipped)`` where ``skipped``
    maps ``strategy`` (every entry gapped) or ``strategy/entry`` (partial
    gap) to the environment-gap reason.  Only classified environment gaps
    skip; any other exception propagates — a repo regression must fail
    the lint, not hide as a skip."""
    lowered: typing.Dict[str, dict] = {}
    skipped: typing.Dict[str, str] = {}
    for name in (strategies or MESH_STRATEGIES):
        strategy = MESH_STRATEGIES[name]
        out, gaps = lower_strategy(strategy, devices)
        if out:
            lowered[name] = out
            for entry, reason in gaps.items():
                skipped[f"{name}/{entry}"] = reason
        elif gaps:
            # every entry gapped: one strategy-level skip, first reason
            skipped[name] = next(iter(gaps.values()))
    return lowered, skipped


# ---- pass 1: per-mesh collective budgets ------------------------------------

def analytic_expectations(strategy: MeshStrategy, mesh_shape,
                          param_bytes: int, entry: str) -> dict:
    """Analytic floor per collective kind, derived from mesh shape x model
    dims — NOT a prediction of XLA's exact op mix (XLA fuses and re-splits
    freely) but a lower bound a real parallel lowering cannot undercut:

    * train under data parallelism: gradients of every
      non-data-sharded param leaf must cross 'data' at least once —
      all-reduce bytes >= ~quarter of param bytes (quarter, not full:
      grads may reduce in bf16 and reduce-scatter splits the kinds).
    * ring SP: at least ``sequence-1`` collective-permutes (one ring).
    * tensor-parallel serving entries: at least one all-reduce (the
      unembed contraction's partial sums).

    ``--write`` refuses budgets below these floors, so the committed
    contract can never encode "the strategy stopped being parallel"."""
    from ..core import sharding as shardlib

    floors: typing.Dict[str, dict] = {}
    data = mesh_shape.get(shardlib.DATA_AXIS, 1)
    seq = mesh_shape.get(shardlib.SEQUENCE_AXIS, 1)
    model = mesh_shape.get(shardlib.MODEL_AXIS, 1)
    if entry.startswith("train_step"):
        if data > 1 or model > 1:
            floors["all-reduce"] = {"min_count": 1,
                                    "min_bytes": param_bytes // 4
                                    if data > 1 else 1}
        if seq > 1:
            floors["collective-permute"] = {"min_count": seq - 1,
                                            "min_bytes": 1}
    elif model > 1:
        floors["all-reduce"] = {"min_count": 1, "min_bytes": 1}
    return floors


def mesh_collective_budget_audit(entry: str, inventory: typing.Mapping,
                                 budget: typing.Mapping,
                                 tolerance: float = DEFAULT_TOLERANCE
                                 ) -> typing.List[Finding]:
    """Fresh census vs the committed per-strategy budget row.  Count and
    bytes are both tolerance-checked; a kind missing from the budget is
    budget 0 (a NEW collective kind is always a finding).  Surplus
    findings name the mesh axes the extra replica groups span."""
    findings: typing.List[Finding] = []
    kinds = sorted(set(inventory) | set(k for k in budget
                                        if isinstance(budget.get(k), dict)))
    for kind in kinds:
        fresh = inventory.get(kind, {"count": 0, "bytes": 0})
        committed = budget.get(kind, {"count": 0, "bytes": 0})
        for metric in ("count", "bytes"):
            a = int(committed.get(metric, 0))
            b = int(fresh.get(metric, 0))
            if abs(b - a) <= max(1 if metric == "count" else 0,
                                 tolerance * a):
                continue
            if b > a:
                axes_new = fresh.get("axes", {})
                axes_old = committed.get("axes", {})
                surplus = {ax: axes_new[ax] - axes_old.get(ax, 0)
                           for ax in axes_new
                           if axes_new[ax] > axes_old.get(ax, 0)}
                where = ", ".join(
                    f"mesh axis '{ax}' (+{n})"
                    for ax, n in sorted(surplus.items())) or "unknown axes"
                findings.append(Finding(
                    "mesh-collective", entry,
                    f"{kind} {metric}={b} over budget {a} "
                    f"(tolerance {tolerance:.0%}) — the surplus "
                    f"collectives reshard over {where}; accidental "
                    "resharding, or if intentional re-run `python -m "
                    "homebrewnlp_tpu.analysis.mesh_audit --write` and "
                    "explain the new comms in the PR"))
            else:
                findings.append(Finding(
                    "mesh-collective", entry,
                    f"{kind} {metric} fell to {b} (budget {a}, tolerance "
                    f"{tolerance:.0%}) — the strategy's comms pattern "
                    "changed underneath the committed budget; if the drop "
                    "is a real win, re-run --write and bank it"))
            break  # one finding per kind is enough signal
    return findings


# ---- pass 2: sharding-spec audit -------------------------------------------

_ENTRY_PARAM_RE = re.compile(
    r"=\s*([a-z0-9]+\[[0-9,]*\])(?:\{[^}]*\})?\s+parameter\((\d+)\)"
    r"(?:[^\n]*?sharding=\{([^}]*)\})?")
_OP_NAME_ATTR_RE = re.compile(r'op_name="([^"]*)"')


def entry_parameters(hlo_text: str) -> typing.List[dict]:
    """``[{index, shape, sharding, op_name}]`` of the ENTRY computation's
    parameters.  jax stamps each with the flattened argument path
    (``op_name="state.variables['...']"``), which is the leaf join — the
    parameter NUMBER shifts when unused args are pruned, the path does
    not."""
    out: typing.List[dict] = []
    in_entry = False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry and line.startswith("}"):
            break
        if not in_entry or " parameter(" not in line:
            continue
        m = _ENTRY_PARAM_RE.search(line)
        if m is None:
            continue
        op = _OP_NAME_ATTR_RE.search(line)
        op_name = op.group(1).replace("\\'", "'") if op else None
        out.append({"index": int(m.group(2)), "shape": m.group(1),
                    "sharding": m.group(3), "op_name": op_name})
    return out


_GATHER_LINE_RE = re.compile(r"=\s*([^=]*?)\s(all-gather)(-start|-done)?\(")


def _gather_result_shapes(hlo_text: str) -> typing.Set[str]:
    """Result shapes of every all-gather instruction.  Anchored between
    the ``=`` and the op token (like the census regex): the op name must
    be followed by ``(``, so instruction NAMES (``%all-gather.3``) and
    operand references on consumer lines never match — only actual
    gather results count.  Async forms: the ``-start`` tuple lists
    (operand, output) so the gathered shape is among its members; the
    ``-done`` twin's result is the output itself."""
    shapes: typing.Set[str] = set()
    for line in hlo_text.splitlines():
        m = _GATHER_LINE_RE.search(line)
        if m is None:
            continue
        for dt, dims in hlo_lint._SHAPE_TOKEN_RE.findall(m.group(1)):
            shapes.add(f"{dt}[{dims}]")
    return shapes


def full_leaf_gathers(hlo_text: str,
                      protected: typing.Mapping[str, dict]
                      ) -> typing.List[str]:
    """Full shapes of protected leaves that some all-gather materialises —
    recorded at ``--write`` time as the reviewed baseline
    (``gather_ok_shapes``), so the audit flags only NEW full-leaf gathers
    (XLA legitimately gathers a small sharded weight where that beats
    partial-sum reduction; the regression is a gather APPEARING where the
    committed program had none)."""
    gathers = _gather_result_shapes(hlo_text)
    return sorted({spec["full"] for spec in protected.values()
                   if spec["full"] in gathers})


def sharding_spec_audit(entry: str, hlo_text: str,
                        protected: typing.Mapping[str, dict],
                        gather_allow: typing.Container[str] = ()
                        ) -> typing.List[Finding]:
    """Protected leaves carry their contracted shard shapes in the
    compiled ENTRY parameters; none is silently replicated, and no
    all-gather outside the committed baseline materialises a
    model-parallel leaf at full shape."""
    findings: typing.List[Finding] = []
    if not protected:
        return findings
    params_tbl = entry_parameters(hlo_text)
    gathers = _gather_result_shapes(hlo_text)
    for leaf, spec in sorted(protected.items()):
        match = [p for p in params_tbl
                 if p["op_name"] and f"'{leaf}'" in p["op_name"]]
        if not match and spec["kind"] == "exact" and "[" not in leaf:
            # batch leaves are labelled batch['token_x'] in train but ride
            # positional tuples elsewhere — fall back to bare-name match
            match = [p for p in params_tbl
                     if p["op_name"] and leaf in p["op_name"]]
        if not match:
            findings.append(Finding(
                "mesh-sharding", entry,
                f"protected leaf {leaf!r} not found among entry "
                "parameters — pruned or relabelled, the sharding audit "
                "cannot see it"))
            continue
        got = match[0]["shape"]
        if spec["kind"] == "exact":
            if got == spec["full"]:
                axes = "/".join(spec.get("axes", [])) or "its mesh axes"
                findings.append(Finding(
                    "mesh-sharding", entry,
                    f"leaf {leaf!r} is SILENTLY REPLICATED: entry "
                    f"parameter carries the full shape {got} instead of "
                    f"the contracted shard {spec['shard']} over {axes} — "
                    "per-chip memory and compute scale as if the axis "
                    "didn't exist"))
            elif got != spec["shard"]:
                findings.append(Finding(
                    "mesh-sharding", entry,
                    f"leaf {leaf!r} entry parameter is {got}, contract "
                    f"expects shard {spec['shard']} (full {spec['full']})"))
        else:  # sharded_any: full-shape parameter = replicated cache
            if got == spec["full"]:
                findings.append(Finding(
                    "mesh-sharding", entry,
                    f"cache leaf {leaf!r} rides the donated carry at FULL "
                    f"shape {got} — the KV pool is replicated per chip "
                    "instead of sharded"))
        if (spec["full"] in gathers and spec["full"] != got
                and spec["full"] not in gather_allow):
            findings.append(Finding(
                "mesh-sharding", entry,
                f"compiler-inserted all-gather materialises {leaf!r} at "
                f"full shape {spec['full']} — a sharded leaf is being "
                "re-assembled per chip (classic accidental-resharding "
                "signature)"))
    return findings


# ---- pass 3: HBM liveness ---------------------------------------------------

_COMP_HEADER_RE = re.compile(r"^(ENTRY\s+)?%?([A-Za-z0-9_.$-]+)\s+\([^)]*\)")
_INSTR_HEAD_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([A-Za-z0-9_.$-]+)\s*=\s*(.*)$")
_OPERAND_RE = re.compile(r"%([A-Za-z0-9_.$-]+)")
_OP_TOKEN_RE = re.compile(
    r"^((?:\([^)]*\)|[a-z0-9]+\[[0-9,]*\](?:\{[^}]*\})?)\s+)?"
    r"([a-zA-Z][\w-]*)\(")

#: result is a VIEW of existing buffers, not an allocation
_VIEW_OPS = frozenset(("parameter", "tuple", "get-tuple-element", "bitcast"))
#: result aliases the operand carry in place (donation-style)
_INPLACE_OPS = frozenset(("while",))


def split_computations(hlo_text: str
                       ) -> typing.Tuple[str, typing.Dict[str, list]]:
    """``(entry_name, {computation: [instruction lines]})``."""
    comps: typing.Dict[str, list] = {}
    entry = ""
    current: typing.Optional[str] = None
    for line in hlo_text.splitlines():
        if line and not line[0].isspace() and "{" in line:
            m = _COMP_HEADER_RE.match(line)
            if m is not None:
                current = m.group(2)
                comps[current] = []
                if m.group(1):
                    entry = current
            continue
        if line.startswith("}"):
            current = None
            continue
        if current is not None and "=" in line:
            comps.setdefault(current, []).append(line)
    return entry, comps


def _segment_bytes(segment: str) -> int:
    return sum(int(np.prod([int(d) for d in dims.split(",") if d]))
               * hlo_lint._DTYPE_BYTES.get(dt, 1)
               for dt, dims in hlo_lint._SHAPE_TOKEN_RE.findall(segment))


def _walk_computation(lines: typing.Sequence[str],
                      comp_peaks: typing.Mapping[str, int],
                      count_params: bool) -> typing.Tuple[int, int]:
    """``(args_bytes, temp_peak)`` of one computation by linear-scan
    liveness: allocations at definition, frees at last textual use, a
    called computation's own internal peak stacked at its call site."""
    parsed = []
    for line in lines:
        m = _INSTR_HEAD_RE.match(line)
        if m is None:
            continue
        name, rhs = m.groups()
        om = _OP_TOKEN_RE.match(rhs)
        if om is None:
            continue
        result_seg, op = om.group(1) or "", om.group(2)
        tail = rhs[om.end():]
        operands = _OPERAND_RE.findall(tail)
        calls = _CALLS_TARGET_RE.findall(tail)
        parsed.append((name, op, _segment_bytes(result_seg), operands,
                       calls))
    last_use: typing.Dict[str, int] = {}
    for i, (_, _, _, operands, _) in enumerate(parsed):
        for o in operands:
            last_use[o] = i
    args_bytes = sum(nbytes for _, op, nbytes, _, _ in parsed
                     if op == "parameter")
    live: typing.Dict[str, int] = {}
    running = 0
    peak = 0
    for i, (name, op, nbytes, operands, calls) in enumerate(parsed):
        alloc = 0
        if op not in _VIEW_OPS and op not in _INPLACE_OPS:
            alloc = nbytes
        running += alloc
        if alloc:
            live[name] = alloc
        # only CONTAINER bodies (while/call/conditional) hold their own
        # HBM-live temporaries; a fusion's intermediates live in
        # registers/scratch, so its ``calls=`` body never stacks here
        callee = 0
        if op in ("while", "call", "conditional"):
            callee = max((comp_peaks.get(c, 0) for c in calls), default=0)
        peak = max(peak, running + callee)
        for o in operands:
            if last_use.get(o) == i and o in live:
                running -= live.pop(o)
    base = args_bytes if count_params else 0
    return args_bytes, base + peak


_CALLS_TARGET_RE = re.compile(
    r"(?:calls|to_apply|body|condition|branch_computations=\{)"
    r"=?%?([A-Za-z0-9_.$-]+)")


def liveness_estimate(hlo_text: str) -> typing.Dict[str, int]:
    """Per-chip peak-HBM estimate of one compiled (per-partition) module:
    ``{"args_bytes", "temp_peak_bytes", "peak_bytes"}``.

    Donated state aliases outputs, so the arguments stay live for the
    whole program and the peak is args + the largest concurrent
    temporaries from the buffer walk.  Fusion bodies allocate nothing
    (fused); while bodies contribute their internal walk at the call
    site.  An ESTIMATE with deterministic bias — the committed value is a
    regression gate (a replicated should-be-sharded buffer inflates it
    far past tolerance), not an allocator reproduction."""
    entry, comps = split_computations(hlo_text)
    # non-entry computations first: their internal peaks feed call sites.
    # Iterate to a fixed point over one dependency level at a time (HLO
    # text orders callees before callers in practice; two passes cover
    # stragglers).
    comp_peaks: typing.Dict[str, int] = {}
    names = [c for c in comps if c != entry]
    for _ in range(2):
        for c in names:
            _, comp_peaks[c] = _walk_computation(comps[c], comp_peaks,
                                                 count_params=False)
    args_bytes, peak = _walk_computation(comps.get(entry, []), comp_peaks,
                                         count_params=True)
    return {"args_bytes": int(args_bytes),
            "temp_peak_bytes": int(peak - args_bytes),
            "peak_bytes": int(peak)}


def hbm_liveness_audit(entry: str, estimate: typing.Mapping[str, int],
                       budget_row: typing.Mapping[str, typing.Any],
                       hbm_bytes: int,
                       tolerance: float = DEFAULT_TOLERANCE
                       ) -> typing.List[Finding]:
    """Fresh liveness estimate within tolerance of the committed
    ``peak_bytes`` AND under the strategy's per-chip HBM."""
    findings: typing.List[Finding] = []
    fresh = int(estimate["peak_bytes"])
    committed = int(budget_row.get("peak_bytes", 0))
    if committed and fresh > committed * (1 + tolerance):
        findings.append(Finding(
            "mesh-liveness", entry,
            f"peak-HBM liveness estimate grew {committed} -> {fresh} "
            f"bytes (> {tolerance:.0%} tolerance) — a buffer that used to "
            "shard is now materialised per chip, or a temporary's live "
            "range exploded; scaled to the real config this is the "
            "OOM-at-32-chips regression.  If intentional, re-run `python "
            "-m homebrewnlp_tpu.analysis.mesh_audit --write`"))
    if fresh > hbm_bytes:
        findings.append(Finding(
            "mesh-liveness", entry,
            f"peak-HBM estimate {fresh} exceeds the strategy's per-chip "
            f"HBM budget {hbm_bytes}"))
    return findings


# ---- budgets: meshes section ------------------------------------------------

def _mesh_budget_row(hlo: str, ctx: dict, strategy: MeshStrategy,
                     entry: str) -> dict:
    inventory = hlo_lint.collective_inventory(hlo, ctx["mesh_shape"])
    est = liveness_estimate(hlo)
    row: typing.Dict[str, typing.Any] = {"collectives": inventory}
    row.update(est)
    baseline_gathers = full_leaf_gathers(hlo, ctx["protected"])
    if baseline_gathers:
        row["gather_ok_shapes"] = baseline_gathers
    ma = getattr(ctx.get("compiled"), "memory_analysis", lambda: None)()
    if ma is not None:
        # informational cross-check, never regression-checked (allocator-
        # and backend-dependent where the walk above is text-determined)
        row["xla_memory"] = {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes)}
    return row


def _write_gate(strategy: MeshStrategy, entry: str, ctx: dict,
                row: dict) -> None:
    """Refuse to commit a budget the analytic model says is degenerate."""
    floors = analytic_expectations(strategy, ctx["mesh_shape"],
                                   ctx["param_bytes"], entry)
    inv = row["collectives"]
    for kind, floor in floors.items():
        got = inv.get(kind, {"count": 0, "bytes": 0})
        if (got["count"] < floor["min_count"]
                or got["bytes"] < floor["min_bytes"]):
            raise ValueError(
                f"--write refused: {strategy.name}/{entry} census "
                f"{kind}={got} is below the analytic floor {floor} "
                f"derived from mesh {ctx['mesh_shape']} x model dims — "
                "the strategy is not actually parallel in this lowering "
                "(broken layout rule?), committing it would bless the "
                "regression")
    allowed = strategy.collective_axes
    for kind, data in inv.items():
        for axes_key in data.get("axes", {}):
            if axes_key == "none":
                continue
            if not set(axes_key.split("+")) <= allowed:
                raise ValueError(
                    f"--write refused: {strategy.name}/{entry} has "
                    f"{kind} over mesh axes {axes_key!r}, outside the "
                    f"strategy's allowed axes {sorted(allowed)} — that is "
                    "resharding, not a budget")


def build_mesh_budgets(lowered=None, skipped=None,
                       existing: typing.Optional[dict] = None) -> dict:
    """The ``meshes`` section: measured budgets per strategy x entry (the
    analytic write-gate applied), ``pending`` rows for strategies the
    current environment cannot lower.  ``existing``: the meshes section
    being REPLACED (so a capable environment's committed entries survive
    a pipeline-incapable --write) — callers writing an alternate
    --budgets file pass that file's own section, never the default's."""
    if lowered is None:
        lowered, skipped = lower_strategies()
    skipped = skipped or {}
    meshes: typing.Dict[str, typing.Any] = {
        "_comment": [
            "Per-mesh budgets (analysis/mesh_audit.py): for each parallel",
            "strategy x entry point, the measured collective census",
            "(count + result bytes + replica-group mesh axes) and the",
            "peak-HBM liveness estimate of the compiled per-chip module",
            "on 8 virtual CPU devices.  graft_lint --mesh checks a fresh",
            "lowering against these within `tolerance`; surplus",
            "collectives are reported WITH the mesh axis they reshard",
            "over.  Regenerate via `python -m",
            "homebrewnlp_tpu.analysis.mesh_audit --write` (an analytic",
            "floor per strategy gates the write, so a degenerate,",
            "non-parallel baseline cannot be committed).  `pending` rows:",
            "the current jax/XLA cannot lower that strategy (reason",
            "recorded); they are skipped loudly until a capable",
            "environment commits real numbers (docs/STATIC_ANALYSIS.md)."],
        "tolerance": DEFAULT_TOLERANCE}
    if existing is None:
        existing = hlo_lint.load_budgets().get("meshes", {})
    for name, strategy in MESH_STRATEGIES.items():
        if name in lowered:
            mesh_shape = None
            entries = {}
            for entry, (hlo, ctx) in lowered[name].items():
                row = _mesh_budget_row(hlo, ctx, strategy, entry)
                _write_gate(strategy, entry, ctx, row)
                entries[entry] = row
                mesh_shape = mesh_shape or ctx["mesh_shape"]
            meshes[name] = {"mesh": mesh_shape, "entries": entries}
            # entries that env-gapped while siblings lowered: keep their
            # committed rows and mark the strategy pending, so the
            # coverage check stays exact and the skip stays legitimate
            gapped = {k.split("/", 1)[1]: r for k, r in skipped.items()
                      if k.startswith(name + "/")}
            if gapped:
                meshes[name]["pending"] = next(iter(gapped.values()))
                for entry in gapped:
                    old_row = existing.get(name, {}).get("entries",
                                                         {}).get(entry)
                    if old_row is not None:
                        entries[entry] = old_row
        else:
            old = existing.get(name, {})
            meshes[name] = {
                "mesh": old.get("mesh"),
                "pending": skipped.get(
                    name, old.get("pending", "not lowerable here"))}
            if old.get("entries"):
                # keep budgets committed by a capable environment
                meshes[name]["entries"] = old["entries"]
    return meshes


def write_mesh_budgets(path: typing.Optional[str] = None,
                       lowered=None, skipped=None) -> str:
    """Regenerate ONLY the ``meshes`` section of budgets.json (the
    ``entry_points`` section belongs to the single-device audit); the
    TARGET file's own pending/committed rows are the carry-over base."""
    p = path or hlo_lint.BUDGETS_PATH
    budgets = hlo_lint.load_budgets(p)
    budgets["meshes"] = build_mesh_budgets(
        lowered, skipped, existing=budgets.get("meshes", {}))
    with open(p, "w") as f:
        json.dump(budgets, f, indent=1, sort_keys=True)
        f.write("\n")
    return p


# ---- coverage + one-call audit ---------------------------------------------

def budget_coverage_audit(budgets: typing.Optional[dict] = None
                          ) -> typing.List[Finding]:
    """budgets.json keys are EXACTLY the registered entry points x
    registered meshes — a stale or orphan row (entry renamed, strategy
    dropped) fails instead of silently auditing nothing."""
    budgets = budgets if budgets is not None else hlo_lint.load_budgets()
    findings: typing.List[Finding] = []
    per_entry = set(budgets.get("entry_points", {}))
    registered = set(entry_points.ENTRY_POINTS)
    for orphan in sorted(per_entry - registered):
        findings.append(Finding(
            "mesh-budget-keys", "analysis/budgets.json",
            f"entry_points row {orphan!r} matches no registered entry "
            "point (analysis/entry_points.py ENTRY_POINTS) — a stale row "
            "audits nothing; delete it or restore the entry"))
    for missing in sorted(registered - per_entry):
        findings.append(Finding(
            "mesh-budget-keys", "analysis/budgets.json",
            f"registered entry point {missing!r} has no entry_points "
            "budget row"))
    meshes = budgets.get("meshes", {})
    mesh_rows = {k for k in meshes
                 if k not in ("tolerance",) and not k.startswith("_")}
    for orphan in sorted(mesh_rows - set(MESH_STRATEGIES)):
        findings.append(Finding(
            "mesh-budget-keys", "analysis/budgets.json",
            f"meshes row {orphan!r} matches no registered strategy "
            "(analysis/mesh_audit.py MESH_STRATEGIES)"))
    for missing in sorted(set(MESH_STRATEGIES) - mesh_rows):
        findings.append(Finding(
            "mesh-budget-keys", "analysis/budgets.json",
            f"registered mesh strategy {missing!r} has no meshes budget "
            "row — run `python -m homebrewnlp_tpu.analysis.mesh_audit "
            "--write`"))
    for name in sorted(mesh_rows & set(MESH_STRATEGIES)):
        row = meshes[name]
        if "pending" in row and "entries" not in row:
            continue
        have = set(row.get("entries", {}))
        want = set(MESH_STRATEGIES[name].entries)
        for orphan in sorted(have - want):
            findings.append(Finding(
                "mesh-budget-keys", f"meshes/{name}",
                f"budget row for entry {orphan!r} which the strategy no "
                "longer lowers"))
        for missing in sorted(want - have):
            findings.append(Finding(
                "mesh-budget-keys", f"meshes/{name}",
                f"strategy entry {missing!r} has no budget row — re-run "
                "--write"))
    return findings


def audit_lowered_meshes(lowered: typing.Mapping[str, dict],
                         skipped: typing.Mapping[str, str],
                         budgets: typing.Optional[dict] = None
                         ) -> typing.List[Finding]:
    """All three pass families over pre-lowered strategies + the coverage
    check."""
    from ..utils import flops as flops_mod

    budgets = budgets if budgets is not None else hlo_lint.load_budgets()
    meshes = budgets.get("meshes", {})
    tol = float(meshes.get("tolerance", DEFAULT_TOLERANCE))
    findings = budget_coverage_audit(budgets)
    # a skip is only legitimate where the committed row AGREES the
    # environment cannot lower it (its ``pending`` marker): committed
    # non-pending budgets whose strategy/entry stopped lowering would
    # otherwise audit nothing while CI stays green — the exact silent
    # pass the skip notices exist to prevent
    for key, reason in sorted(skipped.items()):
        name = key.split("/")[0]
        srow = meshes.get(name, {})
        if "entries" in srow and "pending" not in srow:
            findings.append(Finding(
                "mesh-lowering", key,
                f"strategy has committed (non-pending) budgets but no "
                f"longer lowers here ({reason}) — either the lowering "
                "regressed, or this environment newly lacks support: fix "
                "the lowering, or run `python -m homebrewnlp_tpu."
                "analysis.mesh_audit --write` in this environment to "
                "mark the row pending (keeping the committed entries)"))
    for name, per_entry in lowered.items():
        strategy = MESH_STRATEGIES[name]
        srow = meshes.get(name, {})
        if "entries" not in srow:
            findings.append(Finding(
                "mesh-pending", name,
                "strategy lowers in this environment but its budget row "
                "is pending — commit real budgets via `python -m "
                "homebrewnlp_tpu.analysis.mesh_audit --write`"))
            continue
        hbm = flops_mod.HBM_BYTES.get(strategy.hbm_device,
                                      flops_mod.HBM_BYTES["cpu"])
        for entry, (hlo, ctx) in per_entry.items():
            tag = f"{name}/{entry}"
            budget_row = srow["entries"].get(entry, {})
            inventory = hlo_lint.collective_inventory(hlo,
                                                      ctx["mesh_shape"])
            findings += mesh_collective_budget_audit(
                tag, inventory, budget_row.get("collectives", {}), tol)
            findings += sharding_spec_audit(
                tag, hlo, ctx["protected"],
                gather_allow=budget_row.get("gather_ok_shapes", ()))
            findings += hbm_liveness_audit(
                tag, liveness_estimate(hlo), budget_row, hbm, tol)
    return findings


def audit_meshes(budgets: typing.Optional[dict] = None,
                 devices=None
                 ) -> typing.Tuple[typing.List[Finding],
                                   typing.Dict[str, str]]:
    """``(findings, skipped)`` — the one-call form ``graft_lint --mesh``
    and tier-1 use."""
    lowered, skipped = lower_strategies(devices)
    return audit_lowered_meshes(lowered, skipped, budgets), skipped


# ---- CLI --------------------------------------------------------------------

def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="mesh-aware graft-lint: build / check the per-mesh "
                    "collective + liveness budgets")
    ap.add_argument("--write", action="store_true",
                    help="regenerate the `meshes` section of "
                         "analysis/budgets.json (the budget-update "
                         "protocol, docs/STATIC_ANALYSIS.md)")
    ap.add_argument("--check", action="store_true",
                    help="audit against the committed budgets (default)")
    ap.add_argument("--budgets", default=None,
                    help="alternate budgets.json path")
    args = ap.parse_args(argv)
    if args.write:
        lowered, skipped = lower_strategies()
        p = write_mesh_budgets(args.budgets, lowered, skipped)
        for name, reason in sorted(skipped.items()):
            print(f"mesh-audit: strategy {name!r} pending — environment "
                  f"gap: {reason}")
        print(f"mesh budgets written to {p}")
        return 0
    budgets = hlo_lint.load_budgets(args.budgets) if args.budgets else None
    findings, skipped = audit_meshes(budgets)
    for name, reason in sorted(skipped.items()):
        print(f"mesh-audit: strategy {name!r} SKIPPED — environment gap: "
              f"{reason}")
    for f in findings:
        print(f)
    if findings:
        print(f"mesh-audit: {len(findings)} finding(s)")
        return 1
    print(f"mesh-audit: clean ({len(MESH_STRATEGIES) - len(skipped)} "
          f"strategies audited, {len(skipped)} skipped)")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
