"""Per-entry, per-scope cost ledger (docs/OBSERVABILITY.md 'Cost
attribution').

The model graph carries ``jax.named_scope`` regions (core/scope.py mirrors
every scope frame into jax's name stack), so both jaxpr equations
(``source_info.name_stack``) and compiled-HLO instructions
(``metadata={op_name=...}``) name the block/layer that produced them.  This
module turns that into a budgeted artifact:

* :func:`build_ledger` — for each entry point in
  ``analysis/entry_points.py``, walk the traced jaxpr with
  ``utils.flops.scope_costs`` (matmul FLOPs + unfused bytes per name
  stack), fold stacks into coarse :func:`scope_key` scopes, attach XLA's
  whole-module ``cost_analysis`` numbers, and classify each scope against
  the ``ROOFLINE_DEVICE`` roofline (compute- vs HBM-bound).
* ``analysis/cost_ledger.json`` — the committed ledger;
  :func:`ledger_audit` regression-checks a fresh build against it the way
  ``budgets.json`` gates collectives (drift beyond ``tolerance`` = lint
  finding; update protocol: ``python -m homebrewnlp_tpu.analysis.cost_ledger
  --write`` and review the diff, docs/STATIC_ANALYSIS.md).
* :func:`scope_map_from_hlo` — {instruction name -> op_name} from compiled
  HLO text, the join key ``scripts/attribute_step.py`` uses to attribute
  profiler trace time to the same scopes.

Import stays cheap: jax only inside functions (the AST-only consumers of
the package import this module's :func:`scope_key` without jax).
"""
from __future__ import annotations

import json
import os
import re
import typing

from . import hlo_lint

LEDGER_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "cost_ledger.json")

#: the device kind whose roofline classifies scope bounds in the COMMITTED
#: ledger — a fixed reference chip, so the bound column is deterministic
#: across the CPU test rig and TPU runs (utils/flops.py tables; the v5e is
#: the chip the flagship numbers were measured on)
ROOFLINE_DEVICE = "TPU v5e"

#: relative drift in per-scope flops/bytes the regression check tolerates
DEFAULT_TOLERANCE = 0.05

# ---- scope folding ---------------------------------------------------------

_TRANSFORM_RE = re.compile(r"^[A-Za-z_][A-Za-z_0-9]*\((.*)\)$")
_PHASES = ("input", "body", "output", "loss")
#: named-scope markers that name a region directly (model/decode.py,
#: infer/sampler.py, train/__init__.py)
_SPECIAL = {"cache_read": "decode/cache_read",
            "cache_write": "decode/cache_write",
            "sampling": "decode/sampling",
            "optimizer": "optimizer"}
#: model/frontend.py LAYER_FUNCTIONS keys (mirrored, not imported — this
#: module must stay importable without jax); update together
_LAYER_NAMES = frozenset((
    "feed_forward", "attention", "cummean", "cumsum", "norm", "rezero",
    "activation", "convolution", "dropout", "group_linear", "split_path",
    "feed_forward_product_key_memory", "product_key_memory",
    "reduced_half_linear", "transpose_sequence_features",
    "bottleneck_group_linear", "sum_heads"))


def _unwrap(comp: str) -> str:
    """``"transpose(jvp(gpt0))"`` -> ``"gpt0"``; plain names pass through."""
    while True:
        m = _TRANSFORM_RE.match(comp)
        if m is None:
            return comp
        comp = m.group(1)


def _basename(comp: str) -> str:
    """Strip the scope-counter suffix: ``"attention_1"`` -> ``"attention"``,
    ``"body0"`` -> ``"body"``."""
    return comp.rstrip("0123456789").rstrip("_")


def scope_key(path: str) -> str:
    """Fold a name-stack / HLO ``op_name`` path into a coarse model scope.

    Keys: ``decode/cache_read|cache_write|sampling``, ``optimizer``,
    ``input/embed``, ``input``, ``body/<layer>``, ``output/unembed``,
    ``output``, ``loss``, ``unscoped``.  Transform decorations
    (``jvp``/``transpose``/``jit`` wrappers) are unwrapped, so forward and
    backward ops of one block fold into the same scope — per-block
    attribution, not per-pass."""
    phase = None
    layer = None
    bases = []
    for comp in str(path).split("/"):
        base = _basename(_unwrap(comp))
        bases.append(base)
        if base in _SPECIAL:
            return _SPECIAL[base]
        if phase is None and base in _PHASES:
            phase = base
        elif phase is not None and layer is None and base in _LAYER_NAMES:
            layer = base
    if phase == "body" and layer is not None:
        return f"body/{layer}"
    if phase == "input":
        return "input/embed" if ("embed" in bases or "gather" in bases) \
            else "input"
    if phase == "output":
        return "output/unembed" if "embed" in bases else "output"
    if phase is not None:
        return phase
    return "unscoped"


# ---- ledger build ----------------------------------------------------------

def _fold_scopes(raw: typing.Mapping[str, typing.Tuple[int, int]]
                 ) -> typing.Dict[str, typing.Dict[str, int]]:
    scopes: typing.Dict[str, typing.Dict[str, int]] = {}
    for stack, (fl, by) in raw.items():
        s = scopes.setdefault(scope_key(stack), {"flops": 0, "bytes": 0})
        s["flops"] += int(fl)
        s["bytes"] += int(by)
    return scopes


def _roofline():
    from ..utils import flops as flops_mod
    return (flops_mod.PEAK_TFLOPS[ROOFLINE_DEVICE],
            flops_mod.HBM_BANDWIDTH[ROOFLINE_DEVICE])


def scope_table(jaxpr, peak: typing.Optional[float] = None,
                bandwidth: typing.Optional[float] = None
                ) -> typing.Dict[str, typing.Any]:
    """``{"total": {...}, "scopes": {scope: {flops, bytes, flops_share,
    bytes_share, intensity, bound}}}`` for ONE traced jaxpr — the shared
    core of the per-entry ledger, also consumed directly by ``bench.py``
    (the ``"cost_ledger"`` result key).

    ``peak``/``bandwidth`` override the :data:`ROOFLINE_DEVICE` ridge.
    The committed ledger always classifies against the fixed reference
    chip (determinism across rigs); callers describing a CONCRETE device
    run — bench rows — pass the measured device's roofline instead, so a
    scope isn't labelled hbm-bound by a ridge the benchmarked chip doesn't
    have."""
    from ..utils import flops as flops_mod
    scopes = _fold_scopes(flops_mod.scope_costs(jaxpr))
    tot_f = sum(s["flops"] for s in scopes.values())
    tot_b = sum(s["bytes"] for s in scopes.values())
    ref_peak, ref_bw = _roofline()
    peak = ref_peak if peak is None else peak
    bw = ref_bw if bandwidth is None else bandwidth
    for s in scopes.values():
        s["flops_share"] = round(s["flops"] / tot_f, 6) if tot_f else 0.0
        s["bytes_share"] = round(s["bytes"] / tot_b, 6) if tot_b else 0.0
        s["intensity"] = round(s["flops"] / s["bytes"], 4) if s["bytes"] \
            else 0.0
        s["bound"] = flops_mod.roofline_bound(s["flops"], s["bytes"],
                                              peak, bw)
    return {"total": {"flops": tot_f, "bytes": tot_b,
                      "intensity": round(tot_f / tot_b, 4) if tot_b else 0.0,
                      "bound": flops_mod.roofline_bound(tot_f, tot_b,
                                                        peak, bw)},
            "scopes": scopes}


def _xla_costs(compiled) -> typing.Optional[dict]:
    """Whole-module flops / bytes-accessed from XLA's own cost model —
    recorded for cross-checking the analytical counts, NOT regression-
    checked (backend- and version-dependent)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None
    out = {}
    if ca.get("flops") is not None:
        out["flops"] = float(ca["flops"])
    if ca.get("bytes accessed") is not None:
        out["bytes_accessed"] = float(ca["bytes accessed"])
    return out or None


def build_ledger(lowered: typing.Optional[dict] = None,
                 overrides: typing.Optional[dict] = None) -> dict:
    """The full ledger dict (the ``cost_ledger.json`` schema) from lowered
    entry points (``entry_points.lower_all``; compiled fresh when None)."""
    from . import entry_points
    if lowered is None:
        lowered = entry_points.lower_all(overrides)
    entries = {}
    for entry in entry_points.ENTRY_POINTS:
        _, ctx = lowered[entry]
        table = scope_table(ctx["trace"]())
        xla = _xla_costs(ctx["compiled"])
        if xla is not None:
            table["xla_cost_analysis"] = xla
        entries[entry] = table
    return {
        "_comment": [
            "Per-entry, per-scope cost ledger at the AUDIT_CONFIG scale",
            "(analysis/entry_points.py).  flops: exact matmul FLOPs from",
            "the traced jaxpr (scans x trip count, full-square convention);",
            "bytes: unfused operand+result traffic (uniform upper bound);",
            "bound: compute- vs hbm- against the roofline_device ridge",
            "point.  graft_lint --hlo regression-checks flops/bytes per",
            "scope against a fresh build within `tolerance` — drift means",
            "the model graph's cost structure changed; if intentional, run",
            "`python -m homebrewnlp_tpu.analysis.cost_ledger --write` and",
            "explain the shift in the PR (docs/STATIC_ANALYSIS.md).",
            "xla_cost_analysis is informational (backend-dependent), never",
            "regression-checked."],
        "roofline_device": ROOFLINE_DEVICE,
        "tolerance": DEFAULT_TOLERANCE,
        "entry_points": entries,
    }


# ---- persistence + regression audit ---------------------------------------

def load_ledger(path: typing.Optional[str] = None) -> typing.Optional[dict]:
    p = path or LEDGER_PATH
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


def write_ledger(ledger: typing.Optional[dict] = None,
                 path: typing.Optional[str] = None) -> str:
    p = path or LEDGER_PATH
    ledger = ledger if ledger is not None else build_ledger()
    with open(p, "w") as f:
        json.dump(ledger, f, indent=1, sort_keys=True)
        f.write("\n")
    return p


_UPDATE_HINT = ("if the cost structure changed intentionally, run `python "
                "-m homebrewnlp_tpu.analysis.cost_ledger --write` and "
                "explain the shift in the PR (docs/STATIC_ANALYSIS.md)")


def ledger_audit(lowered: typing.Optional[dict] = None,
                 path: typing.Optional[str] = None,
                 current: typing.Optional[dict] = None
                 ) -> typing.List[hlo_lint.Finding]:
    """Regression-check a fresh ledger build against the committed one.

    Tolerance is RELATIVE per scope per metric; a scope appearing or
    vanishing is always a finding (a new model region must be ledgered, a
    vanished one usually means attribution broke).  Zero-total entries are
    compared structurally only."""
    stored = load_ledger(path)
    if stored is None:
        return [hlo_lint.Finding(
            "cost-ledger", "analysis/cost_ledger.json",
            "ledger file missing — every entry point must carry a committed "
            "cost ledger; " + _UPDATE_HINT)]
    if current is None:
        current = build_ledger(lowered)
    tol = float(stored.get("tolerance", DEFAULT_TOLERANCE))
    findings: typing.List[hlo_lint.Finding] = []
    stored_entries = stored.get("entry_points", {})
    for gone in sorted(set(stored_entries) - set(current["entry_points"])):
        findings.append(hlo_lint.Finding(
            "cost-ledger", gone,
            "entry point vanished from the fresh build but is still in the "
            "committed ledger; " + _UPDATE_HINT))
    for entry, cur in current["entry_points"].items():
        if entry not in stored_entries:
            findings.append(hlo_lint.Finding(
                "cost-ledger", entry,
                "entry point missing from the committed ledger; "
                + _UPDATE_HINT))
            continue
        old = stored_entries[entry]
        old_scopes = old.get("scopes", {})
        cur_scopes = cur["scopes"]
        for gone in sorted(set(old_scopes) - set(cur_scopes)):
            findings.append(hlo_lint.Finding(
                "cost-ledger", entry,
                f"scope {gone!r} vanished from the ledger (attribution "
                "broke, or the region was removed); " + _UPDATE_HINT))
        for new in sorted(set(cur_scopes) - set(old_scopes)):
            findings.append(hlo_lint.Finding(
                "cost-ledger", entry,
                f"scope {new!r} is not in the committed ledger; "
                + _UPDATE_HINT))
        for scope in sorted(set(cur_scopes) & set(old_scopes)):
            for metric in ("flops", "bytes"):
                a = float(old_scopes[scope].get(metric, 0))
                b = float(cur_scopes[scope].get(metric, 0))
                base = max(abs(a), 1.0)
                if abs(b - a) / base > tol:
                    findings.append(hlo_lint.Finding(
                        "cost-ledger", entry,
                        f"scope {scope!r} {metric} drifted "
                        f"{a:.3g} -> {b:.3g} (> {tol:.0%} tolerance); "
                        + _UPDATE_HINT))
    return findings


# ---- HLO instruction -> scope join (scripts/attribute_step.py) -------------

_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([A-Za-z0-9_.$-]+)\s*=\s*"
    r"(?:\([^)]*\)|[a-z0-9]+\[[^\]]*\](?:\{[^}]*\})?)\s+([a-zA-Z][\w-]*)\(")
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?([A-Za-z0-9_.$-]+)\s+\(")
_OP_NAME_RE = re.compile(r'op_name="([^"]+)"')
_CALLS_RE = re.compile(r"(?:calls|to_apply|body)=%?([A-Za-z0-9_.$-]+)")

#: instruction kinds whose profiler event WRAPS its children's events
#: (the body ops report separately) — excluded from attribution totals or
#: every while/call body would double-count
CONTAINER_KINDS = frozenset(("while", "call", "conditional"))


def instruction_table(hlo_text: str
                      ) -> typing.Dict[str, typing.Dict[str, typing.Any]]:
    """``{instruction_name: {"kind", "op_name", "calls"}}`` over every
    instruction of one compiled module's text, computation bodies included.

    Fusion/call instructions often carry no ``op_name`` of their own; their
    scope is inherited from the called computation's ROOT instruction (one
    ``calls=`` hop at lookup time, :func:`attribute_events`)."""
    table: typing.Dict[str, typing.Dict[str, typing.Any]] = {}
    comp_root_op: typing.Dict[str, typing.Optional[str]] = {}
    comp_root_instr: typing.Dict[str, str] = {}
    comp_votes: typing.Dict[str, typing.Dict[str, int]] = {}
    current_comp = None
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            if line and not line[0].isspace():
                c = _COMP_RE.match(line)
                if c is not None:
                    current_comp = c.group(1)
            continue
        name, kind = m.group(1), m.group(2)
        op = _OP_NAME_RE.search(line)
        op_name = op.group(1) if op else None
        calls = _CALLS_RE.search(line)
        table[name] = {"kind": kind, "op_name": op_name,
                       "calls": calls.group(1) if calls else None}
        if current_comp is not None:
            if op_name is not None:
                votes = comp_votes.setdefault(current_comp, {})
                votes[op_name] = votes.get(op_name, 0) + 1
            if line.lstrip().startswith("ROOT "):
                comp_root_instr[current_comp] = name
                if op_name is not None:
                    comp_root_op[current_comp] = op_name
    # a computation's scope: its ROOT's op_name when present, else the
    # majority op_name among its member instructions (fusion roots are
    # often metadata-less bitcasts/copies while the fused math carries the
    # scope)
    comp_op: typing.Dict[str, str] = {}
    for comp, votes in comp_votes.items():
        root = comp_root_op.get(comp)
        comp_op[comp] = root if root is not None else \
            max(votes.items(), key=lambda kv: kv[1])[0]
    # resolve missing op_names through the calls -> computation chain
    # (bounded hops: e.g. call -> computation whose root is a fusion)
    for name, info in table.items():
        comp = info["calls"]
        hops = 0
        while info["op_name"] is None and comp and hops < 4:
            inherited = comp_op.get(comp)
            if inherited is not None:
                info["op_name"] = inherited
                break
            # the called computation carries no metadata anywhere: delegate
            # to whatever ITS root instruction calls (call->fusion chains)
            root = table.get(comp_root_instr.get(comp, ""))
            comp = root["calls"] if root else None
            hops += 1
    return table


def scope_map_from_hlo(hlo_text: str) -> typing.Dict[str, str]:
    """``{instruction_name: op_name}`` (inheritance applied) — profiler
    trace events carry the instruction name (``args.hlo_op``), metadata
    carries the named-scope path; this map is the join between them."""
    return {name: info["op_name"]
            for name, info in instruction_table(hlo_text).items()
            if info["op_name"] is not None}


def _lookup_instr(table: typing.Mapping[str, dict], hlo_op: str
                  ) -> typing.Optional[dict]:
    """The trace's ``hlo_op`` vs the HLO text name can differ by a
    ``.clone`` suffix in either direction (CPU thunks clone parallelized
    fusion roots) — try all three spellings."""
    for cand in (hlo_op, hlo_op + ".clone",
                 hlo_op[:-len(".clone")] if hlo_op.endswith(".clone")
                 else hlo_op):
        info = table.get(cand)
        if info is not None:
            return info
    return None


def attribute_events(events: typing.Iterable[typing.Tuple[str, float]],
                     table: typing.Mapping[str, dict]
                     ) -> typing.Tuple[typing.Dict[str, float],
                                       typing.Dict[str, float], float]:
    """Attribute ``(hlo_op, duration)`` device events to model scopes.

    Returns ``(per_scope_duration, unattributed_by_op, total_duration)``.
    Container instructions (while/call/conditional — their events wrap the
    body ops' own events) are excluded from the total entirely; everything
    else either folds into its :func:`scope_key` or lands in
    ``unattributed`` (which the caller should report loudly — a growing
    unattributed share means the scope annotations or this join broke)."""
    per_scope: typing.Dict[str, float] = {}
    unattr: typing.Dict[str, float] = {}
    total = 0.0
    for hlo_op, dur in events:
        info = _lookup_instr(table, hlo_op)
        if info is not None and info["kind"] in CONTAINER_KINDS:
            continue
        base = hlo_op.split(".")[0]
        if info is None and base in CONTAINER_KINDS:
            continue
        total += dur
        if info is None or info["op_name"] is None:
            unattr[hlo_op] = unattr.get(hlo_op, 0.0) + dur
            per_scope["unattributed"] = per_scope.get("unattributed",
                                                      0.0) + dur
            continue
        key = scope_key(info["op_name"])
        per_scope[key] = per_scope.get(key, 0.0) + dur
    return per_scope, unattr, total


# ---- CLI -------------------------------------------------------------------

def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="build / check the per-scope cost ledger")
    ap.add_argument("--write", action="store_true",
                    help="rebuild analysis/cost_ledger.json from the "
                         "current model (the budget-update protocol)")
    ap.add_argument("--check", action="store_true",
                    help="regression-check against the committed ledger "
                         "(default)")
    ap.add_argument("--path", default=None,
                    help="alternate ledger path (default: "
                         "analysis/cost_ledger.json)")
    args = ap.parse_args(argv)
    if args.write:
        p = write_ledger(path=args.path)
        print(f"cost ledger written to {p}")
        return 0
    findings = ledger_audit(path=args.path)
    for f in findings:
        print(f)
    if findings:
        print(f"cost-ledger: {len(findings)} finding(s)")
        return 1
    print("cost-ledger: clean")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
