"""Deterministic interleaving explorer (graft-lint ``--conc``, half 2).

The serving/elastic control plane is host Python threads mutating shared
state machines (docs/STATIC_ANALYSIS.md 'Concurrency audit'); its
correctness claims — exactly-one-answer, refcount conservation, half-open
single probe, owner-death-never-500s, generation monotonicity — are
schedule-dependent, and pytest's real-thread races reproduce one schedule
per run at the OS scheduler's whim.  This module makes schedules a TEST
INPUT:

* :class:`Explorer` — a cooperative scheduler over real threads where
  exactly ONE logical task runs at a time and control changes hands only
  at explicit switch points, chosen by a seeded RNG.  Same seed + same
  task code => byte-identical schedule (``Explorer.trace``).
* :class:`ExploredLock` — a lock whose acquire/release are switch points
  (preemption injected at every lock boundary).  Reentrant when built via
  ``Explorer.rlock``.  Tasks blocked on a held lock are scheduled only
  when it frees; a state where every live task is blocked raises
  :class:`DeadlockError` naming the wait cycle.
* ``wrap_lock(explorer, obj, attr)`` — swap a real ``threading.Lock`` /
  ``RLock`` attribute for an explored one, so production classes run
  under the explorer unmodified.
* ``instrument(explorer, obj, methods)`` — add switch points at method
  entry/exit for lock-free state machines (BlockPool, CircuitBreaker),
  whose linearization points are their method boundaries.
* :data:`SCENARIOS` — the repo's named invariants, each driven under
  permuted schedules; ``run_scenarios`` returns violations as findings
  for the ``--conc`` CLI.

Device-free: everything here is stdlib + numpy; scenario harnesses
lazy-import their subjects (``infer.paged`` pulls the engine stack).

The explorer's observed lock-order edges (``Explorer.order_edges``) feed
the same cycle checker as the static graph and the runtime traces
(``analysis/conc_lint.py``), so all three views cross-validate.
"""
from __future__ import annotations

import random
import threading
import typing

__all__ = [
    "DeadlockError", "ExplorationLimit", "Explorer", "ExploredLock",
    "VirtualClock", "wrap_lock", "instrument", "SCENARIOS",
    "run_scenarios",
]


class DeadlockError(AssertionError):
    """Every live task is blocked on a lock: the explorer found a real
    deadlock.  ``waiters`` is ``[(task, lock, holder), ...]``; ``trace``
    the schedule that reached it."""

    def __init__(self, message: str, waiters=(), trace=()):
        super().__init__(message)
        self.waiters = list(waiters)
        self.trace = list(trace)


class ExplorationLimit(RuntimeError):
    """The schedule exceeded ``max_switches`` — a livelock (or a scenario
    that genuinely needs a bigger budget)."""


class _TaskAbort(BaseException):
    """Unwinds abandoned task threads on teardown; never escapes."""


class VirtualClock:
    """Injectable monotonic clock: the scheduler advances it one ``tick``
    per context switch, so timeouts and deadlines are schedule-
    deterministic.  Callable, so it drops into every ``clock=`` seam."""

    def __init__(self, start: float = 0.0, tick: float = 0.001):
        self._now = float(start)
        self.tick = float(tick)

    def now(self) -> float:
        return self._now

    __call__ = now

    def advance(self, dt: typing.Optional[float] = None) -> float:
        self._now += self.tick if dt is None else float(dt)
        return self._now


class _Task:
    __slots__ = ("name", "fn", "state", "waiting_on", "thread", "error",
                 "held")

    def __init__(self, name: str, fn: typing.Callable[[], None]):
        self.name = name
        self.fn = fn
        self.state = "new"      # new -> ready -> running -> blocked/done
        self.waiting_on: typing.Optional["ExploredLock"] = None
        self.thread: typing.Optional[threading.Thread] = None
        self.error: typing.Optional[BaseException] = None
        self.held: typing.List["ExploredLock"] = []


class ExploredLock:
    """Mutex whose boundaries are preemption points.  Only valid inside a
    running exploration; outside one (``current task is None``) it
    degrades to no-op bookkeeping so wrapped objects stay importable."""

    def __init__(self, explorer: "Explorer", name: str,
                 reentrant: bool = False):
        self._ex = explorer
        self.name = name
        self.reentrant = reentrant
        self._owner: typing.Optional[_Task] = None
        self._depth = 0

    def _available_to(self, task: _Task) -> bool:
        return self._owner is None or (self.reentrant
                                       and self._owner is task)

    def acquire(self) -> bool:
        ex = self._ex
        task = ex._current_task()
        if task is None:
            return True
        ex._switch(task, f"acquire:{self.name}")
        while not self._available_to(task):
            ex._block(task, self)
        if self._owner is task:
            self._depth += 1
            return True
        self._owner = task
        self._depth = 1
        # observed ordering edges: every lock already held at this acquire
        # is an outer lock of this one (fed to the conc_lint cycle checker)
        for outer in task.held:
            if outer is not self:
                ex.order_edges.add((outer.name, self.name))
        task.held.append(self)
        return True

    def release(self) -> None:
        ex = self._ex
        task = ex._current_task()
        if task is None:
            return
        if self._owner is not task:
            raise RuntimeError(f"task {task.name!r} released "
                               f"{self.name!r} it does not hold")
        self._depth -= 1
        if self._depth == 0:
            self._owner = None
            task.held.remove(self)
        ex._switch(task, f"release:{self.name}")

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._owner is not None


class Explorer:
    """Seed-reproducible cooperative scheduler.

    Register tasks with ``task(fn, name)``, then ``run()``.  Tasks are
    real threads, but exactly one executes between switch points; at each
    point the scheduler picks the next runnable task with its seeded RNG,
    appending to ``trace``.  A task exception aborts the run and re-raises
    in the caller; all-blocked raises :class:`DeadlockError`.
    """

    def __init__(self, seed: int = 0, max_switches: int = 200_000,
                 clock: typing.Optional[VirtualClock] = None):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self.max_switches = int(max_switches)
        self.clock = clock if clock is not None else VirtualClock()
        self.trace: typing.List[str] = []
        self.order_edges: typing.Set[typing.Tuple[str, str]] = set()
        self._tasks: typing.List[_Task] = []
        self._cv = threading.Condition()
        self._running: typing.Optional[_Task] = None
        self._abort = False
        self._locals = threading.local()

    # -- construction --------------------------------------------------------

    def task(self, fn: typing.Callable[[], None],
             name: typing.Optional[str] = None) -> _Task:
        t = _Task(name or f"task{len(self._tasks)}", fn)
        self._tasks.append(t)
        return t

    def lock(self, name: str) -> ExploredLock:
        return ExploredLock(self, name)

    def rlock(self, name: str) -> ExploredLock:
        return ExploredLock(self, name, reentrant=True)

    # -- task-side switch points ---------------------------------------------

    def _current_task(self) -> typing.Optional[_Task]:
        return getattr(self._locals, "task", None)

    def step(self, label: str = "") -> None:
        """Voluntary preemption point (harness code calls this directly;
        locks and ``instrument`` call it for production code)."""
        task = self._current_task()
        if task is not None:
            self._switch(task, label)

    def _switch(self, task: _Task, label: str) -> None:
        with self._cv:
            task.state = "ready"
            self._running = None
            self._cv.notify_all()
            self._cv.wait_for(
                lambda: self._running is task or self._abort)
            if self._abort:
                raise _TaskAbort()
            task.state = "running"

    def _block(self, task: _Task, lock: ExploredLock) -> None:
        with self._cv:
            task.state = "blocked"
            task.waiting_on = lock
            self._running = None
            self._cv.notify_all()
            self._cv.wait_for(
                lambda: self._running is task or self._abort)
            if self._abort:
                raise _TaskAbort()
            task.state = "running"
            task.waiting_on = None

    # -- scheduler -----------------------------------------------------------

    def _runner(self, task: _Task) -> None:
        self._locals.task = task
        try:
            with self._cv:
                task.state = "ready"
                self._cv.notify_all()
                self._cv.wait_for(
                    lambda: self._running is task or self._abort)
                if self._abort:
                    raise _TaskAbort()
                task.state = "running"
            task.fn()
        except _TaskAbort:
            return
        except BaseException as e:  # noqa: BLE001 — re-raised in run()
            task.error = e
        finally:
            with self._cv:
                task.state = "done"
                if self._running is task:
                    self._running = None
                self._cv.notify_all()

    def _runnable(self) -> typing.List[_Task]:
        out = []
        for t in self._tasks:
            if t.state == "ready":
                out.append(t)
            elif t.state == "blocked" and t.waiting_on._available_to(t):
                out.append(t)
        return out

    def _schedulable(self) -> bool:
        if any(t.error is not None for t in self._tasks):
            return True
        if all(t.state == "done" for t in self._tasks):
            return True
        if any(t.state == "new" for t in self._tasks):
            # a thread has not reached its first wait yet — keep waiting
            return False
        return True  # someone is ready/blocked: pick or declare deadlock

    def run(self) -> "Explorer":
        for t in self._tasks:
            t.thread = threading.Thread(
                target=self._runner, args=(t,), daemon=True,
                name=f"interleave-{t.name}")
            t.thread.start()
        try:
            switches = 0
            while True:
                with self._cv:
                    self._cv.wait_for(
                        lambda: self._running is None
                        and self._schedulable())
                    err = next((t for t in self._tasks
                                if t.error is not None), None)
                    if err is not None:
                        raise err.error
                    if all(t.state == "done" for t in self._tasks):
                        return self
                    ready = self._runnable()
                    if not ready:
                        waiters = [(t.name, t.waiting_on.name,
                                    t.waiting_on._owner.name
                                    if t.waiting_on._owner else "?")
                                   for t in self._tasks
                                   if t.state == "blocked"]
                        chain = "; ".join(
                            f"{t} waits on {l} held by {h}"
                            for t, l, h in waiters)
                        raise DeadlockError(
                            f"deadlock under seed {self.seed}: {chain}",
                            waiters=waiters, trace=self.trace)
                    switches += 1
                    if switches > self.max_switches:
                        raise ExplorationLimit(
                            f"schedule exceeded {self.max_switches} "
                            f"switches under seed {self.seed}")
                    choice = ready[self._rng.randrange(len(ready))]
                    self.trace.append(choice.name)
                    self.clock.advance()
                    self._running = choice
                    self._cv.notify_all()
        finally:
            with self._cv:
                self._abort = True
                self._running = None
                self._cv.notify_all()
            for t in self._tasks:
                if t.thread is not None:
                    t.thread.join(timeout=5.0)


# -- adapters for production classes -----------------------------------------

def wrap_lock(explorer: Explorer, obj, attr: str = "_lock",
              name: typing.Optional[str] = None) -> ExploredLock:
    """Replace ``obj.<attr>`` (a ``threading.Lock``/``RLock``) with an
    explored lock so preemption lands at the object's real lock
    boundaries."""
    current = getattr(obj, attr)
    reentrant = isinstance(current, type(threading.RLock()))
    lock = ExploredLock(
        explorer, name or f"{type(obj).__name__}.{attr}", reentrant)
    setattr(obj, attr, lock)
    return lock


def instrument(explorer: Explorer, obj,
               methods: typing.Sequence[str]) -> None:
    """Wrap ``obj``'s methods with entry/exit switch points — the
    preemption seam for LOCK-FREE state machines, whose linearization
    points are their (single-threaded-by-contract) method boundaries."""
    for m in methods:
        fn = getattr(obj, m)

        def wrapped(*a, __fn=fn, __m=m, **kw):
            explorer.step(f"enter:{__m}")
            try:
                return __fn(*a, **kw)
            finally:
                explorer.step(f"exit:{__m}")

        setattr(obj, m, wrapped)


# ============================================================================
# Scenario library: the repo's named invariants under permuted schedules.
# Each scenario takes a seed, runs one exploration, and raises
# AssertionError (message includes the seed + trace tail) on violation.
# ============================================================================

def _fail(explorer: Explorer, message: str) -> typing.NoReturn:
    tail = ",".join(explorer.trace[-12:])
    raise AssertionError(f"{message} [seed={explorer.seed} "
                         f"trace_tail={tail}]")


def scenario_engine_exactly_one_answer(seed: int) -> None:
    """SlotScheduler/EngineController: every submitted request leaves via
    exactly one ``answer`` outcome — across interleaved submits, deadline
    expiry, a failing dispatch, and an open->half_open breaker window —
    and half-open admits exactly one probe into an empty slot set."""
    import numpy as np

    from ..infer.scheduler import EngineController, EngineRequest, \
        SlotScheduler

    ex = Explorer(seed)
    clock = ex.clock

    class _Exec:
        """Deterministic fake executor: advances every live slot one
        position per step; dispatch #3 raises (the device-fault path)."""

        slots, seq = 4, 16

        def __init__(self):
            self.q = np.zeros(self.slots, np.int64)
            self.dispatches = 0

        def admit(self, slot, req):
            self.q[slot] = 0

        def release(self, slot):
            self.q[slot] = 0

        def reset(self):
            self.q[:] = 0

        def tokens(self, slot):
            return [7] * int(self.q[slot])

        def dispatch(self, steps):
            ex.step("dispatch")
            self.dispatches += 1
            if self.dispatches == 3:
                raise RuntimeError("injected device fault")
            self.q = self.q + 1
            return self.q.copy()

    class _Guard:
        """Minimal guard seam: a breaker that opens on the injected fault
        and half-opens one virtual second later."""

        def __init__(self):
            from ..infer.serving_guard import CircuitBreaker
            self.breaker = CircuitBreaker(threshold=1, cooldown_s=0.005,
                                          clock=clock)

        def record_decode_failure(self):
            self.breaker.record_failure()

        def record_decode_success(self):
            self.breaker.record_success()

    answered: typing.Dict[str, typing.List[str]] = {}

    def answer(req, outcome):
        answered.setdefault(req.rid, []).append(outcome[0])

    sched = SlotScheduler(4, clock=clock)
    guard = _Guard()
    ctl = EngineController(_Exec(), sched, guard=guard, clock=clock,
                           decode_chunk=4, answer=answer)
    instrument(ex, sched, ("submit", "admit", "expire", "finish"))

    submitted: typing.List[str] = []

    def producer(tag: str, n: int):
        def fn():
            for i in range(n):
                rid = f"{tag}{i}"
                deadline = clock() + 0.5 if i % 3 else clock() + 0.002
                sched.submit(EngineRequest(
                    rid=rid, path="/token_completion", toks=[1, 2, 3],
                    response_len=2, deadline=deadline))
                submitted.append(rid)
                ex.step("submitted")
        return fn

    def device_loop():
        for _ in range(40):
            ctl.round()
            ex.step("round")
            clock.advance(0.002)
        # drain: give the breaker time to half-open, then finish the rest
        clock.advance(0.01)
        for _ in range(60):
            if sched.depth() == 0:
                break
            # half-open single probe: an empty slot set may admit at most
            # one request while the breaker probes
            if guard.breaker.tick() == "half_open" \
                    and not sched.resident:
                before = len(sched.resident)
                ctl.round()
                if len(sched.resident) - before > 1:
                    _fail(ex, "half-open admitted "
                          f"{len(sched.resident) - before} probes")
            else:
                ctl.round()
            clock.advance(0.002)

    ex.task(producer("a", 5), "producer-a")
    ex.task(producer("b", 5), "producer-b")
    ex.task(device_loop, "device-loop")
    ex.run()
    for rid in submitted:
        n = len(answered.get(rid, ()))
        if n != 1:
            _fail(ex, f"request {rid} answered {n} times "
                  f"(outcomes={answered.get(rid)}) — exactly-one-answer "
                  "violated")
    return ex


def scenario_router_owner_death_never_500(seed: int) -> None:
    """Router + GlobalPrefixIndex + CircuitBreaker under concurrent
    forwards, an owner dying mid-run, and the poll loop's
    ``sync_global_index`` racing the invalidate: clients only ever see
    classified HTTPStatusError payloads (never an unhandled exception),
    and a digest fetched BEFORE ``invalidate_owner`` cannot resurrect the
    dead owner's entries (the owner-generation guard)."""
    from ..infer.router import GlobalPrefixIndex, Replica, Router
    from ..infer.serving_guard import HTTPStatusError

    ex = Explorer(seed)
    clock = ex.clock
    dead = {"idx": None}

    def transport(replica, path, body, timeout, headers=None):
        ex.step(f"transport:{replica.index}:{body.get('op', 'fwd')}")
        if replica.index == dead["idx"]:
            return 500, {"error": "replica crashed"}
        if body.get("op") == "index":
            # replica 1's digest names its cached blocks — computed at
            # fetch time, absorbed later (the race window under test)
            paths = [[1, 2, 3, 4]] if replica.index == 1 else []
            digest = {"block_tokens": 4, "paths": paths}
            ex.step("index-fetched")
            return 200, digest
        if body.get("op") in ("export", "import"):
            return 503, {"error": "no blocks"}
        return 200, {"tokens": [9], "text": "ok"}

    reps = [Replica(i, 9000 + i, clock=clock, breaker_cooldown_s=0.5)
            for i in range(3)]
    router = Router(reps, transport=transport, clock=clock,
                    classes=["prefill", "decode", "decode"],
                    block_tokens=4)
    wrap_lock(ex, router.gindex, "_lock", "GlobalPrefixIndex._lock")
    wrap_lock(ex, router, "_lock", "Router._lock")
    for r in reps:
        wrap_lock(ex, r, "_lock", f"Replica{r.index}._lock")
        instrument(ex, r.breaker, ("tick", "record_failure",
                                   "record_success"))

    # seed ownership: replica 1 (decode) owns the probe prefix
    router.gindex.record([1, 2, 3, 4], 1)
    errors: typing.List[BaseException] = []

    def client(tag: str):
        def fn():
            for i in range(4):
                if tag == "a" and i == 1:
                    dead["idx"] = 1  # owner dies under concurrent load
                try:
                    router.forward("/token_completion",
                                   {"tokens": [1, 2, 3, 4, 5]})
                except HTTPStatusError:
                    pass  # classified degradation is the contract
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                ex.step("answered")
        return fn

    def poller():
        for _ in range(3):
            router.sync_global_index(force=True)
            ex.step("synced")

    ex.task(client("a"), "client-a")
    ex.task(client("b"), "client-b")
    ex.task(poller, "poller")
    ex.run()
    if errors:
        _fail(ex, "owner death surfaced an unclassified error to a "
              f"client: {errors[0]!r} — never-a-500 violated")
    if dead["idx"] is not None:
        owner, _ = router.gindex.lookup([1, 2, 3, 4])
        if owner == dead["idx"]:
            _fail(ex, f"dead replica {dead['idx']} still owns prefix "
                  "entries after invalidate — a stale index digest "
                  "resurrected it (sync-vs-invalidate race)")
    return ex


def scenario_blockpool_refcount_conservation(seed: int) -> None:
    """BlockPool + RadixIndex: under interleaved alloc/share/release/evict
    from two request streams, free + live + cached partitions the pool at
    every boundary, and the index never holds a freed block."""
    from ..infer.paged import BlockPool, RadixIndex

    ex = Explorer(seed)
    pool = BlockPool(8)
    index = RadixIndex(4)
    # composite ops (lookup+addref, alloc+insert, deref+maybe-reclaim,
    # evict) are atomic in the product — the device loop is one thread —
    # so the harness serializes them under one lock and the explorer
    # permutes the ORDER of critical sections across streams
    pool_lock = ex.lock("pool")
    instrument(ex, pool, ("alloc", "addref", "deref", "reclaim"))

    def check():
        free = pool.free_count
        live = pool.live_count
        cached = sum(1 for b in range(pool.num_blocks)
                     if not pool._on_free[b] and pool.refcount(b) == 0)
        if free + live + cached != pool.num_blocks:
            _fail(ex, f"free({free}) + live({live}) + cached({cached}) "
                  f"!= {pool.num_blocks} — pool partition violated")
        for b, node in index._by_block.items():
            if pool._on_free[b]:
                _fail(ex, f"radix index holds FREED block {b}")

    def stream(base: int):
        def fn():
            toks = [base, base + 1, base + 2, base + 3]
            for _ in range(6):
                with pool_lock:
                    full, _, _ = index.lookup(toks)
                    if full:
                        held = full[-1].block
                        pool.addref(held)
                    else:
                        if pool.free_count == 0 \
                                and not index.evict_lru(pool):
                            check()
                            continue
                        held = pool.alloc()
                        index.insert(None, tuple(toks), held)
                    check()
                ex.step("hold")
                with pool_lock:
                    if pool.deref(held) == 0 \
                            and not index.holds(held):
                        pool.reclaim(held)
                    check()
        return fn

    def evictor():
        for _ in range(4):
            with pool_lock:
                index.evict_lru(pool)
                check()
            ex.step("evicted")

    ex.task(stream(10), "stream-a")
    ex.task(stream(20), "stream-b")
    ex.task(evictor, "evictor")
    ex.run()
    check()
    return ex


def scenario_elastic_generation_monotonicity(seed: int) -> None:
    """ElasticAgent lease scans: a stale previous-generation publisher can
    never satisfy the current generation's liveness scan (lease keys embed
    the generation), a live peer is never reported lapsed while it keeps
    beating, and a recorded membership event never un-happens."""
    import tempfile

    from ..distributed.elastic import ElasticAgent

    ex = Explorer(seed)
    clock = ex.clock
    kv: typing.Dict[str, str] = {}
    kv_lock = ex.lock("kv")

    def kv_put(key, value):
        with kv_lock:
            kv[key] = value
        return True

    def kv_dir_get(prefix):
        with kv_lock:
            return [(k, v) for k, v in kv.items()
                    if k.startswith(prefix)]

    class _Rec:
        def record(self, kind, **fields):
            return {}

        def flush(self, reason=""):
            return None

    tmp = tempfile.mkdtemp(prefix="hbnlp-conc-elastic-")

    def agent(pid):
        return ElasticAgent(
            tmp, pid, 2, gen=1, interval_s=0.01, timeout_s=0.05,
            kv_put=kv_put, kv_dir_get=kv_dir_get, clock=clock,
            exit_fn=lambda code: None, recorder=_Rec())

    a0, a1 = agent(0), agent(1)
    a0._started_at = a1._started_at = clock()
    saw_event = {0: None, 1: None}

    def beat(agent_, pid, ticks, then_stop_at=None):
        def fn():
            for i in range(ticks):
                if then_stop_at is not None and i >= then_stop_at:
                    break  # this rank dies: stops publishing
                agent_.tick()
                if agent_.event is not None and saw_event[pid] is None:
                    saw_event[pid] = agent_.event
                if saw_event[pid] is not None and agent_.event is None:
                    _fail(ex, f"rank {pid}'s membership event "
                          "un-happened — monotonicity violated")
                ex.step("beat")
                clock.advance(0.004)
        return fn

    def stale_gen_publisher():
        # a leftover generation-0 process keeps publishing under its OLD
        # keys: it must be invisible to the generation-1 scan
        for i in range(8):
            kv_put("hbnlp/elastic/g0/p1", '{"seq": %d}' % (1000 + i))
            ex.step("stale-beat")
            clock.advance(0.004)

    ex.task(beat(a0, 0, 24), "rank0")
    ex.task(beat(a1, 1, 24, then_stop_at=8), "rank1")
    ex.task(stale_gen_publisher, "stale-gen0")
    ex.run()
    # rank1 stopped beating: rank0 must have detected the lapse (the
    # stale g0 lease for p1 must NOT have kept it alive)
    if a0.event is None:
        _fail(ex, "rank 1 stopped beating but rank 0 never recorded a "
              "membership event — the stale generation-0 lease kept a "
              "dead peer alive (generation monotonicity violated)")
    if 1 not in a0.lapsed:
        _fail(ex, f"rank 0 lapsed={a0.lapsed} does not name rank 1")
    return ex


def scenario_flight_recorder_flush(seed: int) -> None:
    """RotatingJsonl/FlightRecorder: concurrent ``record`` from two tasks
    racing ``flush``: seq is strictly increasing and dense, flush holds
    the lock only for the ring copy (file IO runs outside), and the
    flushed blackbox parses as JSONL whose events are a suffix of what
    was recorded."""
    import json
    import os
    import tempfile

    from ..telemetry.events import FlightRecorder, blackbox_path

    ex = Explorer(seed)
    with tempfile.TemporaryDirectory() as tmp:
        rec = FlightRecorder(capacity=64, clock=ex.clock,
                             wall=ex.clock)
        rec.configure(tmp, "conc")
        wrap_lock(ex, rec, "_lock", "FlightRecorder._lock")

        def writer(tag, n):
            def fn():
                for i in range(n):
                    rec.record("tick", src=tag, i=i)
                    ex.step("recorded")
            return fn

        def flusher():
            for _ in range(4):
                rec.flush(reason="probe")
                ex.step("flushed")

        ex.task(writer("a", 8), "writer-a")
        ex.task(writer("b", 8), "writer-b")
        ex.task(flusher, "flusher")
        ex.run()
        events = rec.events()
        seqs = [e["seq"] for e in events]
        if seqs != sorted(seqs) or len(set(seqs)) != len(seqs):
            _fail(ex, f"ring seq not strictly increasing: {seqs}")
        if len(events) != 16:
            _fail(ex, f"lost update: {len(events)}/16 events survived "
                  "concurrent record()")
        path = blackbox_path(tmp, "conc")
        if not os.path.exists(path):
            _fail(ex, "flush never wrote the blackbox")
        with open(path) as f:
            dumped = [json.loads(line) for line in f if line.strip()]
        dseqs = [e["seq"] for e in dumped if "seq" in e]
        if dseqs != sorted(dseqs):
            _fail(ex, f"flushed blackbox seq out of order: {dseqs}")
    return ex


#: scenario name -> callable(seed); ``--conc`` runs every scenario under
#: ``CONC_SEEDS`` schedules and reports violations as findings
SCENARIOS: typing.Dict[str, typing.Callable[[int], None]] = {
    "engine-exactly-one-answer": scenario_engine_exactly_one_answer,
    "router-owner-death-never-500": scenario_router_owner_death_never_500,
    "blockpool-refcount-conservation":
        scenario_blockpool_refcount_conservation,
    "elastic-generation-monotonicity":
        scenario_elastic_generation_monotonicity,
    "flight-recorder-flush": scenario_flight_recorder_flush,
}

#: default schedule seeds per scenario (each seed is one full permuted
#: schedule; the count trades CPU for interleaving coverage — the conc
#: suite's budget note in docs/STATIC_ANALYSIS.md)
CONC_SEEDS = tuple(range(10))


def run_scenarios(names: typing.Optional[typing.Sequence[str]] = None,
                  seeds: typing.Sequence[int] = CONC_SEEDS,
                  edges: typing.Optional[set] = None
                  ) -> typing.List[typing.Tuple[str, int, str]]:
    """Run each scenario under every seed; returns violations as
    ``(scenario, seed, message)`` (empty = every invariant held).  When
    ``edges`` is a set, every explorer's observed lock-order edges are
    added to it (conc_lint folds them into its ordering cycle check)."""
    out = []
    for name in (names or SCENARIOS):
        fn = SCENARIOS[name]
        for seed in seeds:
            try:
                ex = fn(int(seed))
                if edges is not None and ex is not None:
                    edges.update(ex.order_edges)
            except AssertionError as e:
                out.append((name, int(seed), str(e)))
            except Exception as e:  # noqa: BLE001 — harness fault
                out.append((name, int(seed),
                            f"scenario harness error: {type(e).__name__}: "
                            f"{e}"))
    return out
