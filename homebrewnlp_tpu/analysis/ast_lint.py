"""Repo-specific AST lint rules (graft-lint half b).

Source-level discipline over ``homebrewnlp_tpu/`` and ``scripts/`` —
stdlib-only and importable WITHOUT the package (scripts/check_config_docs.py
loads this file by path; nothing here may import numpy, jax, or siblings):

==============  ============================================================
rule            invariant
==============  ============================================================
wallclock       ``time.time()`` is forbidden — durations on an NTP-stepped
                wall clock corrupted steps_per_sec (the PR 4 MetricLogger
                bug); use ``time.monotonic()``.  Epoch stamps that genuinely
                need wall time (tfevents wall_time, filename stamps) carry
                an allow marker.
unseeded-rng    ``np.random.default_rng()`` with no seed is unreproducible;
                the two deliberate sites (shuffle entropy, data_seed
                generation itself) carry allow markers.
donated-jit     every ``jax.jit(..., donate_argnums=...)`` site must be
                registered in ``DONATED_JIT_REGISTRY`` so the HLO donation
                audit (analysis/hlo_lint.py) covers it — an unregistered
                donation is an unaudited 2x-HBM failure mode.
engine-registry donated jit sites under ``infer/`` must be the Engine's
                single chunk-program builder (``engine.py::_chunk_jit``) or
                the batch sampler's — a donated jit anywhere else in the
                serving tier is a forked carry layout escaping the
                composition registry (``ENGINE_PROGRAMS``); new serving
                features compose as registry rows, not new programs.
mesh-axis-literal  hardcoded mesh-axis name strings ("data", "model",
                "sequence", "pipe") in axis-consuming positions —
                PartitionSpec/NamedSharding arguments, ``mesh.shape``
                subscripts/gets, ``axis_names`` membership tests — outside
                the axis-defining layers (``parallel/``,
                ``core/sharding.py``, ``config.py``).  Use the
                ``core.sharding`` constants (``DATA_AXIS`` ...) so an axis
                rename cannot silently strand a PartitionSpec.
config-docs     every ModelParameter knob has a docs/CONFIG.md table row
                (absorbed from scripts/check_config_docs.py, which now
                shims onto this rule).
metric-docs     every ``hbnlp_*`` metric name registered via a registry
                ``counter()``/``gauge()``/``histogram()`` call must have a
                row in docs/OBSERVABILITY.md's catalog (mirrors the
                config-docs rule; an undocumented series is invisible to
                the operator reading the doc).
==============  ============================================================

Suppression: put ``graft-lint: allow[<rule>]`` in a comment on the
offending line or the line above.  Suppressions are part of the diff and
review like any other code.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
import typing

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
CONFIG_PY = os.path.join(REPO, "homebrewnlp_tpu", "config.py")
CONFIG_MD = os.path.join(REPO, "docs", "CONFIG.md")

#: source trees the repo rules run over (tests/ excluded: harness code
#: times walls and seeds rngs per-test by its own conventions)
LINT_SUBDIRS = ("homebrewnlp_tpu", "scripts")

#: ``file::enclosing-function`` of every ``donate_argnums`` jit site,
#: mapped to the HLO-audit entry point(s) covering it
#: (analysis/entry_points.py).  Adding a donated jit?  Register it here AND
#: give it a lowering + donation audit there — donation is a compiled-
#: artifact property and regresses silently (docs/STATIC_ANALYSIS.md).
DONATED_JIT_REGISTRY: typing.Dict[str, str] = {
    # the donated train step: audited as "train_step"
    "homebrewnlp_tpu/train/__init__.py::_build_step": "train_step",
    # the stepped decode chunk + its cache-initialising first chunk:
    # audited as "decode_chunk_step" and "prefill_entry_step"
    "homebrewnlp_tpu/infer/sampler.py::_jit_sampler":
        "decode_chunk_step, prefill_entry_step",
    # the audit harness's own lowering of the decode step
    "homebrewnlp_tpu/analysis/entry_points.py::lower_decode_step":
        "decode_chunk_step (harness)",
    "homebrewnlp_tpu/analysis/entry_points.py::lower_prefill_entry":
        "prefill_entry_step (harness)",
    # the Engine's single chunk-program builder: every composition in
    # infer/engine.py ENGINE_PROGRAMS (plain / spec / paged /
    # spec-on-paged, each with init/admit/plain phases) lowers through
    # this ONE jit site and is audited under its registry name
    "homebrewnlp_tpu/infer/engine.py::_chunk_jit":
        "engine_chunk_step, spec_chunk_step, paged_chunk_step, "
        "spec_paged_chunk_step",
}

#: the Engine no-fork invariant (the ``engine-registry`` rule): donated
#: jit sites under ``infer/`` build chunk programs, and the ONLY legal
#: chunk-program builders are the Engine's single site and the batch
#: sampler's.  A new donated jit anywhere else in ``infer/`` is a forked
#: carry layout escaping the composition registry — add a row to
#: ``ENGINE_PROGRAMS`` instead of a program.
ENGINE_REGISTRY_SITES = frozenset((
    "homebrewnlp_tpu/infer/engine.py::_chunk_jit",
    "homebrewnlp_tpu/infer/sampler.py::_jit_sampler",
))


#: mesh-axis names the mesh-axis-literal rule polices (mirrors
#: core/sharding.py MESH_AXES — mirrored, not imported: this module must
#: stay importable without jax; tests pin the two in sync)
MESH_AXIS_NAMES = frozenset(("data", "pipe", "model", "sequence"))

#: files/dirs allowed to spell axis names literally: the axis-DEFINING
#: layers.  ``config.py`` derives ``mesh_shape``/``layout`` from knobs and
#: cannot import core.sharding (import cycle), so it stays a defining
#: layer alongside shardlib and the manual-collective kernels
MESH_AXIS_ALLOWED = ("homebrewnlp_tpu/parallel/",
                     "homebrewnlp_tpu/core/sharding.py",
                     "homebrewnlp_tpu/config.py")

#: callee basenames whose string arguments are axis names
_AXIS_CALLEES = ("PartitionSpec", "NamedSharding", "P",
                 "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
                 "all_gather", "psum_scatter", "axis_index", "all_to_all")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation: ``rule``, ``entry`` (``relpath:line``), ``message``."""
    rule: str
    entry: str
    message: str

    def __str__(self):
        return f"[{self.rule}] {self.entry}: {self.message}"


def _suppressed(lines: typing.Sequence[str], lineno: int, rule: str) -> bool:
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines) and f"graft-lint: allow[{rule}]" in lines[ln - 1]:
            return True
    return False


# ---- per-file rules --------------------------------------------------------

def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression (``np.random.default_rng``)."""
    parts: typing.List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


class _FileVisitor(ast.NodeVisitor):
    def __init__(self, rel: str, lines: typing.Sequence[str]):
        self.rel = rel
        self.lines = lines
        self.fn_stack: typing.List[str] = []
        self.findings: typing.List[Finding] = []
        self.axis_exempt = any(
            rel == allow or (allow.endswith("/") and rel.startswith(allow))
            for allow in MESH_AXIS_ALLOWED)
        #: names bound to the time MODULE (``import time [as t]``) and to
        #: the time.time FUNCTION (``from time import time [as now]``) —
        #: the wallclock rule must catch every spelling, not just
        #: ``time.time()``
        self.time_modules: typing.Set[str] = {"time"}
        self.time_funcs: typing.Set[str] = set()

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            if alias.name == "time":
                self.time_modules.add(alias.asname or "time")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    self.time_funcs.add(alias.asname or "time")
        self.generic_visit(node)

    def _add(self, rule: str, node: ast.AST, message: str):
        if not _suppressed(self.lines, node.lineno, rule):
            self.findings.append(
                Finding(rule, f"{self.rel}:{node.lineno}", message))

    def visit_FunctionDef(self, node):
        self.fn_stack.append(node.name)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _is_wallclock(self, name: str) -> bool:
        mod, _, attr = name.rpartition(".")
        return ((attr == "time" and mod in self.time_modules)
                or (not mod and name in self.time_funcs))

    # -- mesh-axis-literal ---------------------------------------------------

    def _axis_literal(self, node: ast.AST, context: str):
        """Flag every mesh-axis-name string constant in ``node``'s subtree
        (axis-consuming position established by the caller)."""
        if self.axis_exempt:
            return
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                    and sub.value in MESH_AXIS_NAMES):
                self._add("mesh-axis-literal", sub,
                          f'hardcoded mesh axis "{sub.value}" in {context} — '
                          "an axis rename silently strands this site; use "
                          "the core.sharding constants (DATA_AXIS, "
                          "MODEL_AXIS, SEQUENCE_AXIS, PIPE_AXIS) or mark "
                          "the line `graft-lint: allow[mesh-axis-literal]`")

    def visit_Subscript(self, node: ast.Subscript):
        if "mesh" in _dotted(node.value).lower():
            self._axis_literal(node.slice, "a mesh-shape subscript")
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare):
        if any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
            others = " ".join(_dotted(c) for c in node.comparators)
            if "axis_names" in others or "mesh_shape" in others \
                    or "mesh" in others.lower():
                self._axis_literal(node.left, "an axis-membership test")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        name = _dotted(node.func)
        base = name.split(".")[-1]
        if base in _AXIS_CALLEES:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                self._axis_literal(arg, f"a {base}(...) argument")
        elif base == "get" and "mesh" in name.lower() and node.args:
            self._axis_literal(node.args[0], "a mesh-shape .get() key")
        if self._is_wallclock(name):
            self._add("wallclock", node,
                      "time.time() is wall clock — an NTP step corrupts "
                      "elapsed-time arithmetic; use time.monotonic() for "
                      "durations (epoch stamps: add a "
                      "`graft-lint: allow[wallclock]` marker)")
        elif name.endswith("default_rng") and not node.args and not node.keywords:
            self._add("unseeded-rng", node,
                      "np.random.default_rng() without a seed is "
                      "unreproducible; seed it (params.data_seed / an "
                      "explicit constant) or mark the line "
                      "`graft-lint: allow[unseeded-rng]`")
        elif name.split(".")[-1] in ("jit", "pjit") and any(
                kw.arg in ("donate_argnums", "donate_argnames")
                for kw in node.keywords):
            fn = self.fn_stack[-1] if self.fn_stack else "<module>"
            key = f"{self.rel}::{fn}"
            if key not in DONATED_JIT_REGISTRY:
                self._add("donated-jit", node,
                          f"donated jit site {key!r} is not in "
                          "analysis/ast_lint.py DONATED_JIT_REGISTRY — "
                          "register it and give it an HLO donation audit "
                          "(analysis/entry_points.py), or the donation can "
                          "silently stop aliasing")
            if (self.rel.startswith("homebrewnlp_tpu/infer/")
                    and key not in ENGINE_REGISTRY_SITES):
                self._add("engine-registry", node,
                          f"donated jit site {key!r} builds a chunk program "
                          "outside the Engine registry — serving carries "
                          "compose through infer/engine.py _chunk_jit "
                          "(add an ENGINE_PROGRAMS row, not a forked "
                          "program; docs/SERVING.md 'Engine architecture')")
        self.generic_visit(node)


def lint_source(rel: str, source: str) -> typing.List[Finding]:
    """Per-file rules over one source blob (``rel`` is the repo-relative
    path used in findings and registry keys)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("parse", f"{rel}:{e.lineno}", f"syntax error: {e.msg}")]
    visitor = _FileVisitor(rel, source.splitlines())
    visitor.visit(tree)
    return visitor.findings


# ---- config-docs rule (absorbed from scripts/check_config_docs.py) ---------

#: internal bookkeeping assigned in the defaults section that is NOT a
#: config knob (everything else there is)
INTERNAL = {"unknown_config_keys"}


def config_knobs(source: str) -> typing.List[str]:
    """``self.X = default`` names from ModelParameter.__init__, up to the
    unknown-key update loop."""
    tree = ast.parse(source)
    init = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "ModelParameter":
            init = next(n for n in node.body
                        if isinstance(n, ast.FunctionDef)
                        and n.name == "__init__")
            break
    if init is None:
        raise AssertionError("ModelParameter.__init__ not found")
    knobs = []
    for stmt in init.body:
        if isinstance(stmt, ast.For):
            # the `for k, v in config.items()` loop ends the defaults
            # section; later assignments are validation/derivation
            break
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, ast.AnnAssign):
            targets = [stmt.target]
        else:
            continue
        for t in targets:
            if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == "self" and not t.attr.startswith("_")
                    and t.attr not in INTERNAL):
                knobs.append(t.attr)
    if len(knobs) < 50:  # the reference schema alone has ~150
        raise AssertionError(f"only {len(knobs)} knobs parsed — the "
                             "defaults-section detection broke")
    return knobs


def documented_keys(md: str) -> typing.Set[str]:
    """Keys of every ``| `name` | ...`` table row."""
    return set(re.findall(r"^\|\s*`([A-Za-z_][A-Za-z_0-9]*)`", md, re.M))


def missing_knobs(config_py: str = CONFIG_PY,
                  config_md: str = CONFIG_MD) -> typing.List[str]:
    with open(config_py) as f:
        knobs = config_knobs(f.read())
    with open(config_md) as f:
        documented = documented_keys(f.read())
    return sorted(set(k for k in knobs if k not in documented))


def config_docs_findings(config_py: str = CONFIG_PY,
                         config_md: str = CONFIG_MD) -> typing.List[Finding]:
    return [Finding("config-docs", "docs/CONFIG.md",
                    f"config knob `{k}` has no docs/CONFIG.md table row "
                    "(add `| `" + k + "` | <default> | <meaning> |`)")
            for k in missing_knobs(config_py, config_md)]


# ---- metric-docs rule (mirrors config-docs) ---------------------------------

OBSERVABILITY_MD = os.path.join(REPO, "docs", "OBSERVABILITY.md")

#: registry factory method names whose first string argument is a metric
#: name (telemetry/registry.py Registry API)
_METRIC_METHODS = frozenset(("counter", "gauge", "histogram"))
_METRIC_PREFIX = "hbnlp_"


def registered_metrics(root: str = REPO,
                       subdirs: typing.Sequence[str] = LINT_SUBDIRS
                       ) -> typing.List[typing.Tuple[str, str, int]]:
    """Every ``hbnlp_*`` metric registered through a literal first argument
    of a ``counter``/``gauge``/``histogram`` call: ``(name, rel, lineno)``.
    Names passed through variables (e.g. ``SPAN_METRIC``) are out of scope
    — the rule polices the literal-registration idiom every layer uses."""
    out: typing.List[typing.Tuple[str, str, int]] = []
    for path, rel in iter_source_files(root, subdirs):
        with open(path) as f:
            src = f.read()
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        lines = src.splitlines()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith(_METRIC_PREFIX)
                    and not _suppressed(lines, node.lineno, "metric-docs")):
                out.append((node.args[0].value, rel, node.lineno))
    return out


def documented_metrics(md: str) -> typing.Set[str]:
    """Every backticked ``hbnlp_*`` name in the doc — generous on purpose:
    a name mentioned anywhere in OBSERVABILITY.md counts as documented."""
    return set(re.findall(r"`(hbnlp_[A-Za-z0-9_]+)`", md))


def metric_docs_findings(root: str = REPO,
                         subdirs: typing.Sequence[str] = LINT_SUBDIRS,
                         obs_md: str = OBSERVABILITY_MD
                         ) -> typing.List[Finding]:
    try:
        with open(obs_md) as f:
            documented = documented_metrics(f.read())
    except OSError:
        documented = set()
    findings, seen = [], set()
    for name, rel, lineno in registered_metrics(root, subdirs):
        if name in documented or name in seen:
            continue
        seen.add(name)
        findings.append(Finding(
            "metric-docs", f"{rel}:{lineno}",
            f"metric `{name}` has no docs/OBSERVABILITY.md catalog row "
            f"(add `| `{name}` | <type> | <labels> | <layer> | <meaning> |`"
            " or mark the line `graft-lint: allow[metric-docs]`)"))
    return findings


# ---- repo walk -------------------------------------------------------------

def iter_source_files(root: str = REPO,
                      subdirs: typing.Sequence[str] = LINT_SUBDIRS
                      ) -> typing.Iterator[typing.Tuple[str, str]]:
    """Yield ``(abs_path, repo_relative_path)`` for every lintable .py."""
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fname in sorted(filenames):
                if fname.endswith(".py"):
                    path = os.path.join(dirpath, fname)
                    yield path, os.path.relpath(path, root)


def lint_repo(root: str = REPO,
              subdirs: typing.Sequence[str] = LINT_SUBDIRS,
              config_docs: bool = True,
              metric_docs: bool = True) -> typing.List[Finding]:
    """All AST rules over the repo: per-file rules + the config-docs and
    metric-docs coverage rules."""
    findings: typing.List[Finding] = []
    for path, rel in iter_source_files(root, subdirs):
        with open(path) as f:
            findings += lint_source(rel, f.read())
    if config_docs:
        findings += config_docs_findings(
            os.path.join(root, "homebrewnlp_tpu", "config.py"),
            os.path.join(root, "docs", "CONFIG.md"))
    if metric_docs:
        findings += metric_docs_findings(
            root, subdirs, os.path.join(root, "docs", "OBSERVABILITY.md"))
    return findings
