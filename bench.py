#!/usr/bin/env python3
"""Headline benchmark: LM tokens/sec/chip on the 32big_mixer recipe.

Prints the headline JSON line {"metric", "value", "unit", "vs_baseline"}
first, then (on success) ONE enriched line adding the long-context
companion keys — consumers should take the LAST line; the early headline
only survives alone if the companion's 16k compile kills the process.

The architecture matches configs/32big_mixer.json of the reference
(/root/reference/configs/32big_mixer.json: seq 512, 8 heads x 512
features/head = d4096, depth 32 x 2 block parts, char vocab 256, bf16,
revnet, adaptive_clip-sm3-momentum-learning_rate); the per-chip batch is
sized for one chip (the reference ran batch 1024 across a 32-core pod =
32/chip; we use 32/chip).  The reference publishes no numbers
(BASELINE.md), so vs_baseline is tracked against the first recorded run of
this benchmark (BENCH_BASELINE.json), giving round-over-round progress.
"""
import argparse
import json
import os
import sys
import time

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_BASELINE.json")

#: ``--check``: the measured headline may drop at most this fraction below
#: the committed per-backend floor before the gate fails (same banding idea
#: as the cost-ledger tolerance: run-to-run noise on shared rigs is real,
#: a structural regression is much larger)
CHECK_TOLERANCE = 0.10

BENCH_CONFIG = {
    "model_mode": "gpt", "use_video": False, "use_language": True,
    "sequence_length": 512, "features_per_head": 512, "heads": 8, "depth": 32,
    "train_batch_size": 32, "vocab_size": 256,
    "calc_accuracy": False, "memory_reduction_strategy": "revnet",
    "block_config": [
        {"layer": ["norm-shift-scale-features-group",
                   "bottleneck_group_linear-in:relu-mid:relu-mid:norm-mid:shift-mid:scale-mid:features"]},
        {"layer": ["norm-shift-scale-features-group",
                   "attention-biased_attention_map-absolute-input_as_value-shared",
                   "norm-shift-scale-features-group", "activation-gelu",
                   "attention-biased_attention_map-absolute-input_as_value-shared"]}],
    "group_linear_factor": 2,
    "intermediate_feed_forward_multiplier_multiplier": 0.5,
    "optimizer": "adaptive_clip:0.003-sm3-momentum:0.9:1:1-learning_rate",
    "learning_rate": 0.01, "weight_decay": 0.0001,
    "learning_rate_config": {"linear_warmup": {"final_step": 4096}},
    "calculation_dtype": "bfloat16", "storage_dtype": "bfloat16",
    "optimizer_slice_dtype": "bfloat16", "slice_dtype": "float32",
    "scale_by_depth": True, "embedding_stddev": 0.004, "z_loss": 1e-4,
    "use_checkpointing": False, "macro_batching": 1,
    "model_path": "/tmp/bench_run",
}

WARMUP_STEPS = 2
MEASURE_STEPS = 10
#: instrumented steps for the phase-attribution companion (run AFTER the
#: headline measurement so its per-step device sync can't touch the number)
PHASE_STEPS = 5


def _ensure_live_backend():
    """The axon TPU plugin blocks interpreter-wide if its tunnel is down;
    probe it in a subprocess and re-exec on CPU when unreachable."""
    if os.environ.get("_BENCH_BACKEND_CHECKED"):
        return
    import subprocess
    env = dict(os.environ, _BENCH_BACKEND_CHECKED="1")
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=120, capture_output=True, env=env)
        ok = probe.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    if not ok:
        print("accelerator backend unreachable; falling back to CPU",
              file=sys.stderr)
        env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        os.execve(sys.executable, [sys.executable] + sys.argv, env)
    os.environ["_BENCH_BACKEND_CHECKED"] = "1"


def compile_probe(steps: int = 2, cache_dir: str = None) -> dict:
    """Cold-vs-warm setup+compile with the persistent compilation cache
    (``compile_cache_dir``, ROADMAP item 4's measurement half).

    Runs the flagship build+warmup twice in FRESH subprocesses sharing one
    cache directory: the first pays the real XLA compile (cold), the
    second should hit the persistent cache (warm).  In-process re-builds
    would hit jax's in-memory cache and prove nothing about restarts —
    the tax this knob exists to kill is the ~100s compile on every
    run_manager relaunch / preemption resume / bench round.

    ``cache_dir`` (``--compile-cache-dir``): probe a PERSISTENT directory —
    the deployment's actual ``compile_cache_dir`` — and RECORD the warm
    verdict there (``utils/compile_cache.py record_reload_verdict``).  A
    reload-broken classification (the jax-0.4.37 CPU deserialization heap
    corruption) then makes ``install_compile_cache`` refuse the cache for
    this backend + jax version with a loud warning instead of letting the
    warm relaunch segfault; a healthy probe (e.g. after a jax upgrade)
    clears the refusal."""
    import contextlib
    import subprocess
    import tempfile
    out = {}
    if cache_dir:
        os.makedirs(cache_dir, exist_ok=True)
        cache_ctx = contextlib.nullcontext(cache_dir)
    else:
        cache_ctx = tempfile.TemporaryDirectory(
            prefix="bench_compile_cache_")
    with cache_ctx as cache:
        prog = (
            "import json, sys, time, os\n"
            "t0 = time.monotonic()\n"
            "import numpy as np, jax, jax.numpy as jnp\n"
            "from homebrewnlp_tpu.config import ModelParameter\n"
            "from homebrewnlp_tpu.model import Model\n"
            "from homebrewnlp_tpu.train import Trainer\n"
            "from homebrewnlp_tpu.utils.compile_cache import \\\n"
            "    install_compile_cache\n"
            "import bench\n"
            "cfg = dict(bench.BENCH_CONFIG)\n"
            "if jax.default_backend() == 'cpu':\n"
            "    cfg.update(sequence_length=64, features_per_head=64,\n"
            "               depth=4, train_batch_size=8)\n"
            f"cfg['compile_cache_dir'] = {cache!r}\n"
            "params = ModelParameter(cfg)\n"
            "install_compile_cache(params)\n"
            "model = Model(params)\n"
            "trainer = Trainer(params, model)\n"
            "rng = np.random.default_rng(0)\n"
            "x = rng.integers(0, params.vocab_size,\n"
            "                 (params.train_batch_size,\n"
            "                  params.sequence_length, 1))\n"
            "batch = {'token_x': jnp.asarray(x),\n"
            "         'token_y': jnp.asarray((x + 1) % params.vocab_size)}\n"
            "state = trainer.init_state(batch)\n"
            "t1 = time.monotonic()\n"
            f"for _ in range({steps}):\n"
            "    state, metrics = trainer.step(state, batch)\n"
            "float(metrics['loss'])\n"
            "t2 = time.monotonic()\n"
            "print(json.dumps({'setup_s': round(t1 - t0, 2),\n"
            "                  'compile_warmup_s': round(t2 - t1, 2),\n"
            "                  'total_s': round(t2 - t0, 2)}))\n")
        for phase in ("cold", "warm"):
            # bypass any recorded refusal inside the probe itself: the
            # re-probe of an armed dir must exercise the cache for real
            env = dict(os.environ, _BENCH_BACKEND_CHECKED="1",
                       HBNLP_COMPILE_CACHE_IGNORE_VERDICT="1")
            res = subprocess.run(
                [sys.executable, "-c", prog],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True, text=True, timeout=1800, env=env)
            if res.returncode != 0:
                # measured round-11 finding on the CPU rig: the COLD run
                # populates the cache fine, but jax-0.4.37's CPU backend
                # corrupts the heap DESERIALIZING the cached executables on
                # the warm relaunch (SIGSEGV/SIGABRT, "corrupted size vs.
                # prev_size"; minimal pure-jax programs reload fine, the
                # train-step mix does not).  Same environment-gap class as
                # the pallas interpret / PartitionId gaps: report the
                # evidence instead of dying, so the probe still lands the
                # verdict in BASELINE.md and a capable env measures the
                # real delta
                out[phase] = {
                    "crashed": True, "returncode": res.returncode,
                    "classified": "jax-0.4.37 cpu persistent-cache "
                                  "deserialization heap corruption "
                                  "(environment gap; docs/PERFORMANCE.md "
                                  "'Round 11')",
                    "stderr_tail": res.stderr[-300:].strip()}
                continue
            out[phase] = json.loads(res.stdout.strip().splitlines()[-1])
    if not (out["cold"].get("crashed") or out["warm"].get("crashed")):
        out["compile_speedup"] = round(
            out["cold"]["compile_warmup_s"]
            / max(out["warm"]["compile_warmup_s"], 1e-9), 2)
    if cache_dir:
        # arm (or clear) install_compile_cache's refusal for this
        # backend+jax version.  A warm crash after a healthy cold run is
        # the reload-broken signature; BOTH runs healthy clears it.  A
        # crashed COLD run is no evidence about reloads at all (the dir
        # may already hold entries a pre-populated deserialization choked
        # on, or the build is just broken) — leave any existing verdict
        # untouched rather than disarming the guard on it
        from homebrewnlp_tpu.utils.compile_cache import record_reload_verdict
        if out["cold"].get("crashed"):
            out["reload_verdict"] = None
            out["reload_broken"] = None  # no evidence — verdict unchanged
        else:
            broken = bool(out["warm"].get("crashed"))
            evidence = (out["warm"].get("classified", "")
                        if broken else "warm reload healthy")
            out["reload_verdict"] = record_reload_verdict(
                cache_dir, broken, evidence=evidence)
            out["reload_broken"] = broken
    return out


def check_floor(value: float, backend: str) -> int:
    """``--check``: nonzero when the measured headline tokens/sec/chip
    falls below the committed per-backend floor minus the tolerance band
    (BENCH_BASELINE.json ``floor`` keys; mirrors ``bench_serving.py
    --check``).  No committed floor for this backend = loud failure, not a
    vacuous pass."""
    try:
        with open(BASELINE_FILE) as f:
            baselines = json.load(f)
    except (OSError, ValueError) as exc:
        print(f"--check: cannot read {BASELINE_FILE}: {exc}",
              file=sys.stderr)
        return 1
    floor = (baselines.get(backend) or {}).get("floor")
    if not floor:
        print(f"--check: no committed floor for backend {backend!r} in "
              f"{BASELINE_FILE} — commit one from a healthy run",
              file=sys.stderr)
        return 1
    limit = float(floor) * (1.0 - CHECK_TOLERANCE)
    verdict = "PASS" if value >= limit else "FAIL"
    print(f"--check [{verdict}]: {value:.0f} tokens/sec/chip vs floor "
          f"{float(floor):.0f} (-{CHECK_TOLERANCE:.0%} band = {limit:.0f}, "
          f"backend {backend})", file=sys.stderr)
    return 0 if value >= limit else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero when the flagship tokens/sec/chip "
                         "drops below the committed floor "
                         "(BENCH_BASELINE.json, tolerance-banded) — the "
                         "headline-perf regression gate")
    ap.add_argument("--compile-probe", action="store_true",
                    help="measure cold-vs-warm setup+compile with the "
                         "persistent compilation cache in two fresh "
                         "subprocesses, print the JSON, and exit")
    ap.add_argument("--compile-cache-dir", default=None,
                    dest="compile_cache_dir",
                    help="with --compile-probe: probe THIS persistent dir "
                         "(the deployment's compile_cache_dir) and record "
                         "the reload verdict there — a reload-broken env "
                         "then refuses the cache at install time instead "
                         "of segfaulting the warm relaunch")
    args = ap.parse_args(argv)
    _ensure_live_backend()
    if args.compile_probe:
        print(json.dumps({"compile_probe": compile_probe(
            cache_dir=args.compile_cache_dir)}), flush=True)
        return 0
    import numpy as np
    t_setup = time.monotonic()
    import jax
    import jax.numpy as jnp
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer

    cfg = dict(BENCH_CONFIG)
    if jax.default_backend() == "cpu":
        # CPU fallback so the benchmark always yields a number
        cfg.update(sequence_length=64, features_per_head=64, depth=4,
                   train_batch_size=8)

    rng = np.random.default_rng(0)

    def build(cfg):
        params = ModelParameter(cfg)
        model = Model(params)
        trainer = Trainer(params, model)

        def make_batch():
            x = rng.integers(0, params.vocab_size,
                             (params.train_batch_size,
                              params.sequence_length, 1))
            return {"token_x": jnp.asarray(x),
                    "token_y": jnp.asarray((x + 1) % params.vocab_size)}

        state = trainer.init_state(make_batch())
        print(f"setup {time.monotonic() - t_setup:.1f}s; compiling...",
              file=sys.stderr)
        t_compile = time.monotonic()
        for _ in range(WARMUP_STEPS):
            state, metrics = trainer.step(state, make_batch())
        # sync by materialising the value: the axon tunnel's
        # block_until_ready can return before the dispatched chain has
        # executed; producing the float forces the chain to completion
        float(metrics["loss"])
        print(f"compile+warmup {time.monotonic() - t_compile:.1f}s",
              file=sys.stderr)
        return params, trainer, state, make_batch

    retry = False
    try:
        params, trainer, state, make_batch = build(cfg)
    except Exception as exc:  # insurance: halve the batch once on OOM
        if "memory" not in str(exc).lower() and "RESOURCE" not in str(exc):
            raise
        print(f"OOM at batch {cfg['train_batch_size']}; retrying at half",
              file=sys.stderr)
        retry = True
    if retry:
        # retry outside the handler so the failed attempt's frames (and the
        # device buffers they pin) are released first
        import gc
        gc.collect()
        cfg["train_batch_size"] //= 2
        params, trainer, state, make_batch = build(cfg)

    batches = [make_batch() for _ in range(MEASURE_STEPS)]
    t0 = time.monotonic()
    for batch in batches:
        state, metrics = trainer.step(state, batch)
    final_loss = float(metrics["loss"])  # value fetch = true device sync
    dt = time.monotonic() - t0

    # step-phase attribution (docs/OBSERVABILITY.md): a short instrumented
    # pass so BENCH_* files carry data-wait / dispatch / device-block
    # medians and prefetcher stall totals, not just the end-to-end number.
    # Runs on a PRIVATE registry after the headline loop — the per-step
    # sync it needs cannot contaminate the headline measurement.
    telemetry_summary = None
    try:
        from homebrewnlp_tpu import telemetry
        from homebrewnlp_tpu.data.inputs import Prefetcher
        reg = telemetry.Registry()
        prev_reg = telemetry.set_registry(reg)
        try:
            phases = telemetry.StepPhases(registry=reg)
            mono = time.monotonic
            feed = Prefetcher((make_batch() for _ in range(PHASE_STEPS)),
                              depth=2, telemetry_label="bench")
            try:
                for _ in range(PHASE_STEPS):
                    tp0 = mono()
                    b = next(feed)
                    tp1 = mono()
                    phases.data_wait.rec(tp0, tp1 - tp0)
                    state, pm = trainer.step(state, b)
                    tp2 = mono()
                    phases.dispatch.rec(tp1, tp2 - tp1)
                    float(pm["loss"])  # device sync attributes device time
                    phases.device_block.rec(tp2, mono() - tp2)
            finally:
                # a mid-pass failure must not leak the fill thread and its
                # pinned batches into the decode companion's memory budget
                feed.close()
            telemetry_summary = telemetry.summarize(reg.snapshot())
        finally:
            telemetry.set_registry(prev_reg)
    except Exception as exc:
        print(f"telemetry phase attribution failed: {exc}", file=sys.stderr)

    tokens = MEASURE_STEPS * params.train_batch_size * params.sequence_length
    n_chips = max(1, len(jax.devices()))
    tokens_per_sec_chip = tokens / dt / n_chips

    # val loss: the driver metric is tokens/sec/chip + VAL LOSS
    # (BASELINE.json); held-out batches from the same synthetic stream,
    # forward-only with dropout off (Trainer.eval_loss)
    try:
        val_losses = [float(trainer.eval_loss(state, make_batch())["loss"])
                      for _ in range(4)]
        val_loss = sum(val_losses) / len(val_losses)
    except Exception as exc:
        print(f"val loss computation failed: {exc}", file=sys.stderr)
        val_loss = None

    # MFU: exact matmul FLOPs from the jaxpr, 3x-forward convention (no
    # rematerialization credit — revnet's recompute is not "useful" FLOPs).
    # Dual convention: "mfu" counts causally-dead flash cells as useful
    # (full-square, stable round-over-round); "mfu_causal" excludes them
    # (the executed-FLOP denominator; emitted when the model has causal
    # flash kernels)
    try:
        from homebrewnlp_tpu.utils.flops import forward_flops_split, mfu
        fwd_flops, fwd_exec = forward_flops_split(
            lambda v, b: trainer.model.apply(v, b).total_loss.data,
            state.variables, batches[0])
        mfu_frac = mfu(fwd_flops, dt / MEASURE_STEPS, n_chips)
        mfu_causal = mfu(fwd_exec, dt / MEASURE_STEPS, n_chips)
    except Exception as exc:
        print(f"MFU computation failed: {exc}", file=sys.stderr)
        mfu_frac = mfu_causal = None

    # collective census of the headline train step (docs/STATIC_ANALYSIS.md):
    # BENCH_*.json tracks comms growth round over round the same way it
    # tracks tokens/sec — an unexplained new collective kind in the trend is
    # accidental resharding.  Needs a second compile of the step (the
    # executed jit's compiled module is not retrievable), so it runs only
    # where that is cheap (CPU fallback shapes) unless BENCH_COLLECTIVES=1
    # forces it; BENCH_COLLECTIVES=0 disables it everywhere.
    collectives = None
    want = os.environ.get("BENCH_COLLECTIVES", "auto")
    if want != "0" and (want != "auto" or jax.default_backend() == "cpu"):
        try:
            from homebrewnlp_tpu.analysis import hlo_lint
            hlo = trainer.lowered(state, batches[0]).compile().as_text()
            collectives = hlo_lint.collective_census(hlo)
        except Exception as exc:
            print(f"collective census failed: {exc}", file=sys.stderr)

    # per-scope cost ledger of the headline step (docs/OBSERVABILITY.md
    # 'Cost attribution'): BENCH_*.json rows become self-attributing —
    # which block holds the FLOPs/bytes, and what each is bound by.  A
    # trace of the already-built step (no second compile); env gate
    # mirrors BENCH_COLLECTIVES (BENCH_COST_LEDGER=1 forces on TPU, =0
    # disables).
    cost_ledger_tab = None
    want_cl = os.environ.get("BENCH_COST_LEDGER", "auto")
    if want_cl != "0" and (want_cl != "auto"
                           or jax.default_backend() == "cpu"):
        try:
            from homebrewnlp_tpu.analysis import cost_ledger as cl
            from homebrewnlp_tpu.utils import flops as flops_mod
            traced = trainer._step_fn.trace(state, batches[0],
                                            jax.random.PRNGKey(0))
            # bench rows describe THIS device run: classify bounds against
            # the measured chip's ridge, not the committed ledger's fixed
            # reference chip (cost_ledger.ROOFLINE_DEVICE)
            dev = jax.devices()[0]
            cost_ledger_tab = cl.scope_table(
                traced.jaxpr, peak=flops_mod.peak_flops(dev),
                bandwidth=flops_mod.peak_hbm_bandwidth(dev))
            cost_ledger_tab["roofline_device"] = str(
                getattr(dev, "device_kind", jax.default_backend()))
        except Exception as exc:
            print(f"cost ledger failed: {exc}", file=sys.stderr)

    # first recorded value per backend becomes the baseline; later runs
    # report progress against it (batch size is part of the config identity
    # so an OOM-halved run never corrupts the full-batch baseline)
    vs_baseline = 1.0
    backend = jax.default_backend()
    config_id = f"32big_mixer/1chip/b{params.train_batch_size}"
    baselines = {}
    try:
        if os.path.exists(BASELINE_FILE):
            with open(BASELINE_FILE) as f:
                baselines = json.load(f)
        prior = baselines.get(backend, {})
        if prior.get("value") and prior.get("config", config_id) == config_id:
            vs_baseline = tokens_per_sec_chip / float(prior["value"])
        elif prior.get("config", config_id) == config_id:
            baselines[backend] = {"value": tokens_per_sec_chip,
                                  "config": config_id,
                                  "time": time.time()}
            with open(BASELINE_FILE, "w") as f:
                json.dump(baselines, f)
    except (OSError, ValueError):
        pass

    print(f"final loss {final_loss:.4f}", file=sys.stderr)
    out = {"metric": "LM tokens/sec/chip @ 32big_mixer",
           "value": round(tokens_per_sec_chip, 2),
           "unit": "tokens/sec/chip",
           "vs_baseline": round(vs_baseline, 4),
           # what vs_baseline compares against: the first recorded run of
           # THIS benchmark (round 1), not the MTF reference — the reference
           # publishes no single-chip numbers and pod hardware for a direct
           # loss/throughput comparison is unavailable (BASELINE.md)
           "baseline_ref": "round1 self-baseline (BENCH_BASELINE.json); "
                           "MTF comparison hardware-blocked"}
    if mfu_frac is not None:
        out["mfu"] = round(mfu_frac, 4)
    if mfu_causal is not None and round(mfu_causal, 4) != round(mfu_frac, 4):
        out["mfu_causal"] = round(mfu_causal, 4)
    if val_loss is not None:
        out["val_loss"] = round(val_loss, 4)
    if telemetry_summary is not None:
        out["telemetry"] = telemetry_summary
    if collectives is not None:
        out["collectives"] = collectives
    if cost_ledger_tab is not None:
        out["cost_ledger"] = cost_ledger_tab
    # the headline line goes out NOW: the companion's 16k compile can kill
    # the PROCESS (worker crash / OOM), which no except clause survives — a
    # consumer taking the last JSON line sees the enriched line when the
    # companion succeeds and this one when it dies
    print(json.dumps(out), flush=True)

    if args.check:
        # gate mode: the verdict is about the headline number only — skip
        # the companion benches so a CI gate pays one build, not five
        return check_floor(tokens_per_sec_chip, backend)

    def companion(label: str, prefix: str, run_fn, keys=(),
                  value_key: str = "value",
                  value_dst: str = "_tokens_per_sec_chip"):
        """Run one companion bench, merge its result under ``prefix`` onto
        the headline line, re-print the enriched line.  Returns False when
        the companion failed (the printed line so far still stands).  A
        companion returning a dict WITHOUT ``value_key`` (e.g. an error
        dict) is reported as a failed companion instead of aborting the
        remaining companions with a KeyError."""
        try:
            res = run_fn()
            if not isinstance(res, dict) or value_key not in res:
                raise KeyError(f"companion result has no {value_key!r}: "
                               f"{str(res)[:200]}")
        except Exception as exc:
            print(f"{label} companion bench failed: {exc}", file=sys.stderr)
            return False
        out[prefix + value_dst] = res[value_key]
        for key, dst in (("metric", f"{prefix}_metric"),
                         ("mfu", f"{prefix}_mfu"),
                         ("mfu_causal", f"{prefix}_mfu_causal"),
                         *keys):
            if key in res:
                out[dst] = res[key]
        print(json.dumps(out), flush=True)
        return True

    # long-context companion measurement (seq 16,384 on TPU; shrunk on CPU —
    # its 'metric' string names the actual sequence length): the flagship
    # line alone would hide the framework's long-context throughput
    # (BASELINE.md 'Long context')
    state = trainer = batches = None  # free HBM before the 16k compile
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "scripts"))
    import bench_long_context as lc
    lc_ok = companion("long-context", "long_context", lc.run)

    if lc_ok and jax.default_backend() != "cpu":
        # 32k companion (TPU only — the CPU fallback would shrink to the
        # same shape as the 16k companion): the longest context one chip
        # trains; the fused backward admits its 4.3GB dq-partial buffer
        # through the memory-aware default cap — no env override needed
        companion("32k", "long_context_32k", lambda: lc.run(seq=32768))

        # routed-MoE companion: the EP component's standing throughput
        # number (configs/moe_mixer.json, BASELINE.md round 5)
        def run_moe():
            import bench_moe
            return bench_moe.run()
        companion("moe", "moe", run_moe,
                  keys=(("expert_utilization_min_at_init",
                         "moe_expert_utilization_min_at_init"),))

    # decode-latency companion (every backend; shapes shrink on CPU): the
    # sequence-scaling probe as a TRACKED metric — ms/token at 8k/16k/32k
    # with bf16 and int8 caches, plus the 32k/8k per-token-vs-byte ratio
    # that caught the cache-carry copy bug (BASELINE.md round 5)
    def run_decode():
        import bench_decode
        return bench_decode.run()
    companion("decode", "decode", run_decode,
              keys=(("rows", "decode_rows"),
                    ("scaling_ratio_large_small",
                     "decode_scaling_ratio_large_small"),
                    ("byte_ratio_large_small",
                     "decode_byte_ratio_large_small")),
              value_dst="_ms_per_token")
    return 0


if __name__ == "__main__":
    sys.exit(main())
