// Native byte-level BPE tokenizer trainer.
//
// The reference trains its 65,536-token BPE with a gcc-compiled Cython module
// (/root/reference/scripts/train_tokenizer.pyx) around the HuggingFace
// trainer; this is the rebuild's native equivalent: the full trainer — word
// counting, pair statistics, and incremental merge updates — in C++, exposed
// as plain C symbols for ctypes (no pybind11 in this image).
//
// Semantics mirror the reference's tokenizer construction
// (train_tokenizer.pyx:180-188): the corpus is pre-tokenized with the
// "isolated" split — every ASCII digit / whitespace / punctuation byte is its
// own pre-token, maximal runs of all other bytes form words — and the
// initial alphabet is the 256 single bytes (the reference's chr(0..255)
// special tokens).  Training is classic BPE: repeatedly merge the most
// frequent adjacent symbol pair, maintaining pair counts incrementally (only
// words containing the merged pair are touched) with a lazy max-heap, so the
// merge loop is O(touched words) per step rather than a full recount.
//
// Build: g++ -O3 -march=native -shared -fPIC bpe_trainer.cpp -o libbpe.so -pthread
#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// "isolated" split classes: ASCII digits, whitespace, punctuation each form
// a single-byte pre-token; everything else (incl. bytes >= 128) is a word
// byte.  Matches string.digits + whitespace + string.punctuation.
bool is_split_byte(unsigned char b) {
    if (b >= '0' && b <= '9') return true;
    switch (b) {
        case ' ': case '\t': case '\n': case '\r': case '\v': case '\f':
            return true;
        default: break;
    }
    // ASCII punctuation: 33-47, 58-64, 91-96, 123-126
    if ((b >= 33 && b <= 47) || (b >= 58 && b <= 64) ||
        (b >= 91 && b <= 96) || (b >= 123 && b <= 126)) return true;
    return false;
}

using WordCounts = std::unordered_map<std::string, int64_t>;

struct Range {
    const std::string* path;
    int64_t start, end;  // [start, end) plus the word spanning `end`
};

// Count pre-token words of one byte range.  A word spanning `end` belongs to
// this range (we read past end to finish it); a word spanning `start`
// belongs to the previous range (we skip to the first split byte unless the
// byte at start-1 is already a boundary).  Split bytes are single-byte
// pre-tokens, but one-symbol words never produce pairs, so they're skipped.
// Boundary bytes are ASCII, so ranges never cut UTF-8 sequences ambiguously.
bool count_range(const Range& r, WordCounts& out) {
    FILE* f = fopen(r.path->c_str(), "rb");
    if (!f) return false;
    bool skipping = false;
    int64_t pos = r.start;
    if (r.start > 0) {
        if (fseek(f, (long)(r.start - 1), SEEK_SET) != 0) { fclose(f); return false; }
        int prev = fgetc(f);
        if (prev == EOF) { fclose(f); return true; }
        skipping = !is_split_byte((unsigned char)prev);
    }
    std::vector<unsigned char> buf(1 << 20);
    std::string word;
    bool done = false;
    while (!done) {
        size_t got = fread(buf.data(), 1, buf.size(), f);
        if (got == 0) break;
        for (size_t i = 0; i < got; i++, pos++) {
            unsigned char b = buf[i];
            if (is_split_byte(b)) {
                if (skipping) {
                    skipping = false;
                } else if (word.size() > 1) {
                    out[word]++;
                }
                word.clear();
                // a word owns the range its first byte is in; anything after
                // this boundary starts at pos+1
                if (pos + 1 >= r.end) { done = true; break; }
            } else if (!skipping) {
                word.push_back((char)b);
            } else if (pos >= r.end) {
                // the skipped word extends past our range: nothing left for us
                done = true;
                word.clear();
                break;
            }
        }
    }
    if (word.size() > 1) out[word]++;
    fclose(f);
    return true;
}

int64_t file_size(const std::string& path) {
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) return -1;
    fseek(f, 0, SEEK_END);
    int64_t size = ftell(f);
    fclose(f);
    return size;
}

inline uint64_t pack(int32_t a, int32_t b) {
    return ((uint64_t)(uint32_t)a << 32) | (uint32_t)b;
}

// Decode one UTF-8 codepoint at s[i]; on malformed input falls back to the
// single byte's value (latin-1 style), so arbitrary bytes still train.
uint32_t decode_utf8(const std::string& s, size_t& i) {
    unsigned char b = (unsigned char)s[i];
    if (b < 0x80) { i++; return b; }
    int n = (b >= 0xF0) ? 4 : (b >= 0xE0) ? 3 : (b >= 0xC0) ? 2 : 1;
    if (n == 1 || i + (size_t)n > s.size()) { i++; return b; }
    uint32_t cp = b & (0x7Fu >> n);
    for (int k = 1; k < n; k++) {
        unsigned char c = (unsigned char)s[i + k];
        if ((c & 0xC0) != 0x80) { i++; return b; }
        cp = (cp << 6) | (c & 0x3F);
    }
    i += (size_t)n;
    return cp;
}

struct HeapEntry {
    int64_t count;
    uint64_t pair;
    bool operator<(const HeapEntry& o) const {
        if (count != o.count) return count < o.count;
        return pair > o.pair;  // deterministic: lower pair id wins ties
    }
};

}  // namespace

extern "C" {

// Train BPE merges over newline-joined `paths`.  Pre-tokens are split on raw
// bytes (the split classes are pure ASCII, so byte and codepoint splitting
// agree on UTF-8 input); initial symbols are unicode codepoints — ids 0..255
// fixed (the chr(0..255) specials), higher codepoints assigned ids 256+ in
// sorted order ("A <codepoint> <id>" lines), then merges ("M left right
// count" lines) continue the id space in merge order.  Returns the number of
// merges, or negative on error (-1 bad args / open failure, -2 no trainable
// words).
long bpe_train(const char* paths_joined, long vocab_size, long min_frequency,
               long n_threads, const char* out_path) {
    if (!paths_joined || !out_path || vocab_size <= 256) return -1;
    std::vector<std::string> paths;
    {
        const char* p = paths_joined;
        while (*p) {
            const char* nl = strchr(p, '\n');
            size_t len = nl ? (size_t)(nl - p) : strlen(p);
            if (len) paths.emplace_back(p, len);
            p += len + (nl ? 1 : 0);
        }
    }
    if (paths.empty()) return -1;
    if (n_threads <= 0) n_threads = 1;

    // ---- parallel word counting over byte ranges -------------------------
    // files are split into ~equal ranges aligned at split-byte boundaries by
    // count_range's ownership rule, so a single big corpus file still uses
    // every thread
    std::vector<Range> ranges;
    {
        int64_t total = 0;
        std::vector<int64_t> sizes(paths.size());
        for (size_t i = 0; i < paths.size(); i++) {
            sizes[i] = file_size(paths[i]);
            if (sizes[i] < 0) return -1;
            total += sizes[i];
        }
        int64_t chunk = total / (4 * n_threads) + 1;
        if (chunk < (1 << 20)) chunk = 1 << 20;
        for (size_t i = 0; i < paths.size(); i++) {
            for (int64_t start = 0; start < sizes[i]; start += chunk) {
                int64_t end = start + chunk < sizes[i] ? start + chunk : sizes[i];
                ranges.push_back({&paths[i], start, end});
            }
        }
    }
    WordCounts words;
    {
        std::mutex mu;
        std::atomic<size_t> next{0};
        std::atomic<bool> ok{true};
        std::vector<std::thread> threads;
        long nt = n_threads < (long)ranges.size() ? n_threads : (long)ranges.size();
        for (long t = 0; t < nt; t++) {
            threads.emplace_back([&]() {
                WordCounts local;
                while (true) {
                    size_t i = next.fetch_add(1);
                    if (i >= ranges.size()) break;
                    if (!count_range(ranges[i], local)) ok = false;
                }
                std::lock_guard<std::mutex> lock(mu);
                for (auto& kv : local) words[kv.first] += kv.second;
            });
        }
        for (auto& th : threads) th.join();
        if (!ok) return -1;
    }
    if (words.empty()) return -2;

    // ---- alphabet: codepoints >= 256 get ids 256+ in sorted order ----------
    std::vector<uint32_t> high_cps;
    {
        std::unordered_map<uint32_t, char> seen;
        for (auto& kv : words) {
            const std::string& w = kv.first;
            for (size_t i = 0; i < w.size();) {
                uint32_t cp = decode_utf8(w, i);
                if (cp >= 256 && !seen.count(cp)) {
                    seen[cp] = 1;
                    high_cps.push_back(cp);
                }
            }
        }
        std::sort(high_cps.begin(), high_cps.end());
    }
    std::unordered_map<uint32_t, int32_t> cp_to_id;
    for (size_t i = 0; i < high_cps.size(); i++)
        cp_to_id[high_cps[i]] = (int32_t)(256 + i);

    // ---- pair statistics ---------------------------------------------------
    size_t n_words = words.size();
    std::vector<std::vector<int32_t>> syms(n_words);
    std::vector<int64_t> wcount(n_words);
    {
        size_t i = 0;
        for (auto& kv : words) {
            const std::string& w = kv.first;
            syms[i].reserve(w.size());
            for (size_t j = 0; j < w.size();) {
                uint32_t cp = decode_utf8(w, j);
                syms[i].push_back(cp < 256 ? (int32_t)cp : cp_to_id[cp]);
            }
            wcount[i] = kv.second;
            i++;
        }
        words.clear();
    }

    std::unordered_map<uint64_t, int64_t> pair_count;
    std::unordered_map<uint64_t, std::vector<int32_t>> pair_words;
    pair_count.reserve(1 << 20);
    for (size_t w = 0; w < n_words; w++) {
        const auto& s = syms[w];
        for (size_t i = 0; i + 1 < s.size(); i++) {
            uint64_t pr = pack(s[i], s[i + 1]);
            pair_count[pr] += wcount[w];
            auto& vec = pair_words[pr];
            if (vec.empty() || vec.back() != (int32_t)w) vec.push_back((int32_t)w);
        }
    }

    std::priority_queue<HeapEntry> heap;
    for (auto& kv : pair_count) heap.push({kv.second, kv.first});
    if (min_frequency < 1) min_frequency = 1;

    FILE* out = fopen(out_path, "w");
    if (!out) return -1;
    for (size_t i = 0; i < high_cps.size(); i++)
        fprintf(out, "A %u %d\n", high_cps[i], (int32_t)(256 + i));

    long target_merges = vocab_size - 256 - (long)high_cps.size();
    long n_merges = 0;
    int32_t next_id = (int32_t)(256 + high_cps.size());
    std::vector<uint64_t> touched;
    while (n_merges < target_merges && !heap.empty()) {
        HeapEntry top = heap.top();
        heap.pop();
        auto it = pair_count.find(top.pair);
        if (it == pair_count.end() || it->second != top.count) continue;  // stale
        if (top.count < min_frequency) break;
        int32_t a = (int32_t)(top.pair >> 32), b = (int32_t)(uint32_t)top.pair;
        int32_t t = next_id++;
        fprintf(out, "M %d %d %lld\n", a, b, (long long)top.count);
        n_merges++;
        pair_count.erase(it);

        touched.clear();
        auto occ_it = pair_words.find(top.pair);
        if (occ_it != pair_words.end()) {
            std::vector<int32_t> occ = std::move(occ_it->second);
            pair_words.erase(occ_it);
            for (int32_t w : occ) {
                auto& s = syms[w];
                // does this word still contain (a, b)?
                bool has = false;
                for (size_t i = 0; i + 1 < s.size(); i++)
                    if (s[i] == a && s[i + 1] == b) { has = true; break; }
                if (!has) continue;
                int64_t wc = wcount[w];
                // retire old adjacent-pair counts for the whole word
                for (size_t i = 0; i + 1 < s.size(); i++) {
                    uint64_t pr = pack(s[i], s[i + 1]);
                    auto pit = pair_count.find(pr);
                    if (pit != pair_count.end()) {
                        pit->second -= wc;
                        touched.push_back(pr);
                    }
                }
                // rewrite the word with the merged symbol
                std::vector<int32_t> ns;
                ns.reserve(s.size());
                for (size_t i = 0; i < s.size();) {
                    if (i + 1 < s.size() && s[i] == a && s[i + 1] == b) {
                        ns.push_back(t);
                        i += 2;
                    } else {
                        ns.push_back(s[i]);
                        i++;
                    }
                }
                s = std::move(ns);
                // add new adjacent-pair counts
                for (size_t i = 0; i + 1 < s.size(); i++) {
                    uint64_t pr = pack(s[i], s[i + 1]);
                    pair_count[pr] += wc;
                    touched.push_back(pr);
                    auto& vec = pair_words[pr];
                    if (vec.empty() || vec.back() != w) vec.push_back(w);
                }
            }
        }
        // re-queue every touched pair at its current count (lazy heap)
        for (uint64_t pr : touched) {
            auto pit = pair_count.find(pr);
            if (pit != pair_count.end() && pit->second > 0)
                heap.push({pit->second, pr});
        }
    }
    fclose(out);
    return n_merges;
}

}  // extern "C"
