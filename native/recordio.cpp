// Native TFRecord scanner + Example int64/bytes feature fast paths.
//
// The reference's data prep used gcc-compiled Cython for its CPU-bound hot
// loops (/root/reference/scripts/local_text2tfrecord.pyx,
// train_tokenizer.pyx); this plays the same role for the training-time input
// pipeline: record-frame scanning and packed-varint decoding are the per-byte
// loops Python is worst at.  Exposed via ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -march=native -shared -fPIC recordio.cpp -o librecordio.so
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>

extern "C" {

// Scan TFRecord framing; fill payload offsets/lengths. Returns record count,
// -1 on open failure, -2 if out arrays are too small.
long rio_scan(const char* path, int64_t* offsets, int64_t* lengths, long max_n) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    long n = 0;
    int64_t pos = 0;
    unsigned char header[12];
    while (true) {
        size_t got = fread(header, 1, 12, f);
        if (got < 12) break;
        uint64_t len;
        memcpy(&len, header, 8);
        if (n >= max_n) { fclose(f); return -2; }
        offsets[n] = pos + 12;
        lengths[n] = (int64_t)len;
        n++;
        pos += 12 + (int64_t)len + 4;
        if (fseek(f, (long)(len + 4), SEEK_CUR) != 0) break;
    }
    fclose(f);
    return n;
}

// Read the whole file into caller-provided buffer. Returns bytes read or -1.
long rio_read_file(const char* path, unsigned char* buf, long cap) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    long total = 0;
    while (total < cap) {
        size_t got = fread(buf + total, 1, (size_t)(cap - total), f);
        if (got == 0) break;
        total += (long)got;
    }
    fclose(f);
    return total;
}

static inline uint64_t read_varint(const unsigned char* buf, long* pos) {
    uint64_t result = 0;
    int shift = 0;
    while (true) {
        unsigned char b = buf[*pos];
        (*pos)++;
        result |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) return result;
        shift += 7;
    }
}

// Decode a packed-varint int64 run into out. Returns count (<= max_out).
long rio_decode_varints(const unsigned char* buf, long len, int64_t* out,
                        long max_out) {
    long pos = 0, n = 0;
    while (pos < len && n < max_out) {
        uint64_t v = read_varint(buf, &pos);
        out[n++] = (int64_t)v;
    }
    return n;
}

// Locate the value payload of a named feature inside a serialized Example.
// kind_out: 1=bytes, 2=float, 3=int64. Returns payload length and sets
// *offset_out, or -1 if absent/malformed.
long rio_find_feature(const unsigned char* buf, long len, const char* name,
                      long* offset_out, int* kind_out) {
    long pos = 0;
    long name_len = (long)strlen(name);
    while (pos < len) {
        uint64_t tag = read_varint(buf, &pos);
        if ((tag >> 3) != 1 || (tag & 7) != 2) return -1;
        uint64_t flen = read_varint(buf, &pos);           // Features
        long fend = pos + (long)flen;
        while (pos < fend) {
            uint64_t etag = read_varint(buf, &pos);       // map entry
            uint64_t elen = read_varint(buf, &pos);
            long eend = pos + (long)elen;
            bool match = false;
            (void)etag;
            while (pos < eend) {
                uint64_t itag = read_varint(buf, &pos);
                uint64_t ilen = read_varint(buf, &pos);
                if ((itag >> 3) == 1) {                   // key
                    match = ((long)ilen == name_len &&
                             memcmp(buf + pos, name, (size_t)name_len) == 0);
                    pos += (long)ilen;
                } else {                                  // Feature value
                    if (match) {
                        long vpos = pos;
                        uint64_t ftag = read_varint(buf, &vpos); // oneof field
                        uint64_t flen2 = read_varint(buf, &vpos);
                        long lend = vpos + (long)flen2;
                        uint64_t ltag = read_varint(buf, &vpos); // .value
                        (void)ltag; (void)lend;
                        uint64_t vlen = read_varint(buf, &vpos);
                        *offset_out = vpos;
                        *kind_out = (int)(ftag >> 3);
                        return (long)vlen;
                    }
                    pos += (long)ilen;
                }
            }
            pos = eend;
        }
        pos = fend;
    }
    return -1;
}

}  // extern "C"
