// Native TFRecord scanner + Example int64/bytes feature fast paths.
//
// The reference's data prep used gcc-compiled Cython for its CPU-bound hot
// loops (/root/reference/scripts/local_text2tfrecord.pyx,
// train_tokenizer.pyx); this plays the same role for the training-time input
// pipeline: record-frame scanning and packed-varint decoding are the per-byte
// loops Python is worst at.  Exposed via ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -march=native -shared -fPIC recordio.cpp -o librecordio.so
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>

extern "C" {

// Scan TFRecord framing; fill payload offsets/lengths. Returns record count,
// -1 on open failure, -2 if out arrays are too small.
long rio_scan(const char* path, int64_t* offsets, int64_t* lengths, long max_n) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    long n = 0;
    int64_t pos = 0;
    unsigned char header[12];
    while (true) {
        size_t got = fread(header, 1, 12, f);
        if (got < 12) break;
        uint64_t len;
        memcpy(&len, header, 8);
        if (n >= max_n) { fclose(f); return -2; }
        offsets[n] = pos + 12;
        lengths[n] = (int64_t)len;
        n++;
        pos += 12 + (int64_t)len + 4;
        if (fseek(f, (long)(len + 4), SEEK_CUR) != 0) break;
    }
    fclose(f);
    return n;
}

// Read the whole file into caller-provided buffer. Returns bytes read or -1.
long rio_read_file(const char* path, unsigned char* buf, long cap) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    long total = 0;
    while (total < cap) {
        size_t got = fread(buf + total, 1, (size_t)(cap - total), f);
        if (got == 0) break;
        total += (long)got;
    }
    fclose(f);
    return total;
}

static inline uint64_t read_varint(const unsigned char* buf, long* pos) {
    uint64_t result = 0;
    int shift = 0;
    while (true) {
        unsigned char b = buf[*pos];
        (*pos)++;
        result |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) return result;
        shift += 7;
    }
}

// Decode a packed-varint int64 run into out. Returns count (<= max_out).
long rio_decode_varints(const unsigned char* buf, long len, int64_t* out,
                        long max_out) {
    long pos = 0, n = 0;
    while (pos < len && n < max_out) {
        uint64_t v = read_varint(buf, &pos);
        out[n++] = (int64_t)v;
    }
    return n;
}

// Locate the value payload of a named feature inside a serialized Example.
// kind_out: 1=bytes, 2=float, 3=int64. Returns payload length and sets
// *offset_out, or -1 if absent/malformed.
long rio_find_feature(const unsigned char* buf, long len, const char* name,
                      long* offset_out, int* kind_out) {
    long pos = 0;
    long name_len = (long)strlen(name);
    while (pos < len) {
        uint64_t tag = read_varint(buf, &pos);
        if ((tag >> 3) != 1 || (tag & 7) != 2) return -1;
        uint64_t flen = read_varint(buf, &pos);           // Features
        long fend = pos + (long)flen;
        while (pos < fend) {
            uint64_t etag = read_varint(buf, &pos);       // map entry
            uint64_t elen = read_varint(buf, &pos);
            long eend = pos + (long)elen;
            bool match = false;
            (void)etag;
            while (pos < eend) {
                uint64_t itag = read_varint(buf, &pos);
                uint64_t ilen = read_varint(buf, &pos);
                if ((itag >> 3) == 1) {                   // key
                    match = ((long)ilen == name_len &&
                             memcmp(buf + pos, name, (size_t)name_len) == 0);
                    pos += (long)ilen;
                } else {                                  // Feature value
                    if (match) {
                        long vpos = pos;
                        uint64_t ftag = read_varint(buf, &vpos); // oneof field
                        uint64_t flen2 = read_varint(buf, &vpos);
                        long lend = vpos + (long)flen2;
                        uint64_t ltag = read_varint(buf, &vpos); // .value
                        (void)ltag; (void)lend;
                        uint64_t vlen = read_varint(buf, &vpos);
                        *offset_out = vpos;
                        *kind_out = (int)(ftag >> 3);
                        return (long)vlen;
                    }
                    pos += (long)ilen;
                }
            }
            pos = eend;
        }
        pos = fend;
    }
    return -1;
}

// ---- writer: crc32c (Castagnoli) + framed record emission ---------------

static uint32_t crc_table[8][256];
static bool crc_init_done = false;

static void crc_init() {
    if (crc_init_done) return;
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
        crc_table[0][i] = c;
    }
    // slice-by-8 tables
    for (uint32_t i = 0; i < 256; i++)
        for (int t = 1; t < 8; t++)
            crc_table[t][i] = crc_table[0][crc_table[t - 1][i] & 0xFF]
                              ^ (crc_table[t - 1][i] >> 8);
    crc_init_done = true;
}

static uint32_t crc32c_raw(const unsigned char* buf, int64_t len) {
    crc_init();
    uint32_t crc = 0xFFFFFFFFu;
    while (len >= 8) {
        uint64_t word;
        memcpy(&word, buf, 8);
        word ^= crc;
        crc = crc_table[7][word & 0xFF] ^ crc_table[6][(word >> 8) & 0xFF]
            ^ crc_table[5][(word >> 16) & 0xFF] ^ crc_table[4][(word >> 24) & 0xFF]
            ^ crc_table[3][(word >> 32) & 0xFF] ^ crc_table[2][(word >> 40) & 0xFF]
            ^ crc_table[1][(word >> 48) & 0xFF] ^ crc_table[0][(word >> 56) & 0xFF];
        buf += 8;
        len -= 8;
    }
    while (len-- > 0)
        crc = crc_table[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
    return crc ^ 0xFFFFFFFFu;
}

static uint32_t masked_crc32c(const unsigned char* buf, int64_t len) {
    uint32_t crc = crc32c_raw(buf, len);
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8u);
}

// Masked crc32c of a buffer (TFRecord checksum).
uint32_t rio_masked_crc(const unsigned char* buf, int64_t len) {
    return masked_crc32c(buf, len);
}

// Append n framed records (payloads packed in `buf` at offsets/lengths) to
// `path`.  TFRecord framing: u64 length | u32 masked-crc(length) | payload
// | u32 masked-crc(payload).  Returns n, or -1 on open/write failure.
long rio_write_records(const char* path, const unsigned char* buf,
                       const int64_t* offsets, const int64_t* lengths,
                       long n, int append) {
    FILE* f = fopen(path, append ? "ab" : "wb");
    if (!f) return -1;
    for (long i = 0; i < n; i++) {
        unsigned char header[12];
        uint64_t len = (uint64_t)lengths[i];
        memcpy(header, &len, 8);
        uint32_t hcrc = masked_crc32c(header, 8);
        memcpy(header + 8, &hcrc, 4);
        const unsigned char* payload = buf + offsets[i];
        uint32_t pcrc = masked_crc32c(payload, lengths[i]);
        if (fwrite(header, 1, 12, f) != 12 ||
            fwrite(payload, 1, (size_t)lengths[i], f) != (size_t)lengths[i] ||
            fwrite(&pcrc, 1, 4, f) != 4) {
            fclose(f);
            return -1;
        }
    }
    if (fclose(f) != 0) return -1;
    return n;
}

}  // extern "C"
