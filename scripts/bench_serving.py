#!/usr/bin/env python3
"""Serving traffic generator: batch-to-completion vs continuous batching.

Drives the REAL REST path — ``rest_api.serve`` with its isolated device
loop, HTTP child, Manager IPC, admission control — with a reproducible
mixed-length workload (short and long prompts x short and long responses,
the regime where batch-to-completion pins a whole co-batch on its longest
row), in two generator modes per engine:

* **closed loop** — C workers each firing its next request the moment the
  previous answer lands (saturation throughput), then
* **open loop** — seeded-exponential interarrivals at a target rate, each
  request on its own thread (latency under a Poisson-ish load, the number
  p99 TTFT is about).

Per engine it reports client-side tokens/sec + request outcomes and the
server-side p50/p99 TTFT + ITL scraped from ``/metrics`` (the engine
records TTFT per slot event, the batch path per stepped-loop hook — the
bench config forces ``decode_loop=stepped`` so both sides report), and
writes a BENCH_*-style row to ``BENCH_SERVING.json``.

Acceptance (ISSUE 7): on the CPU backend the continuous engine sustains
>= 1.5x the batch engine's closed-loop tokens/sec at mixed lengths with a
lower open-loop p99 TTFT; the exit code enforces it under ``--check``.

Fault schedules: ``--latency I:SEC[,I:SEC...]`` wraps the interface in
``utils.fault_injection.FaultyInterface`` (the PR 3 schedules) — decode
call I sleeps SEC first.  The schedules fire on ``complete_tokens*`` calls,
i.e. the BATCH engine's decode path (the continuous engine drives the model
directly); use them to reproduce deadline/429 behavior under a stalling
batch decode.

CPU-scale model by default (harness-size mixer, seq 64); pass a config
JSON via ``--config`` to run a real checkpoint's shape instead.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

#: harness-scale serving model: small enough that one decode iteration is
#: milliseconds on CPU, deep/wide enough that the slot pool is a real
#: multi-leaf cache pytree (depth-stacked KV + int8-composable layout)
BENCH_CONFIG = {
    "model_mode": "gpt", "use_video": False, "use_language": True,
    "sequence_length": 64, "features_per_head": 16, "heads": 2,
    "depth": 2, "train_batch_size": 1, "vocab_size": 256,
    "group_linear_factor": 2,
    "intermediate_feed_forward_multiplier_multiplier": 0.5,
    "memory_reduction_strategy": "none",
    "block_config": [
        {"layer": ["norm-shift-scale-features-group",
                   "bottleneck_group_linear-in:relu-mid:relu-mid:norm-mid:"
                   "shift-mid:scale-mid:features"]},
        {"layer": ["norm-shift-scale-features-group",
                   "attention-biased_attention_map-absolute-input_as_value-"
                   "shared"]}],
    # the stepped loop on BOTH engines: it is what reports TTFT/ITL on the
    # batch path, and fine chunks are what let the continuous engine
    # recycle finished slots quickly (chunk boundaries = scheduling points)
    "decode_loop": "stepped", "decode_chunk_tokens": 4,
    "serve_prefill_chunk_tokens": 8,
    "serve_queue_limit": 256, "serve_request_deadline_s": 120.0,
    "model_path": "/tmp/bench_serving",
}

#: mixed request classes (prompt_tokens, max_tokens): the short/long mix
#: that makes batch-to-completion pay head-of-line blocking
WORKLOAD = ((3, 4), (5, 8), (2, 16), (6, 48), (4, 4), (3, 32))

# ---- speculative A/B (--spec; docs/SERVING.md 'Speculative decoding') ------
#
# Acceptance rate is the whole economics of spec decoding, and a RANDOM
# target is the one regime where no cheap draft can exist: an untrained
# full-width model is an incompressible random function, so a narrow
# draft predicts nothing (measured: 15-19% argmax agreement even after
# distillation).  Production pairs work because BOTH models are trained on
# the same distribution; the A/B reproduces exactly that: a tiny
# deterministic language (a fixed random permutation map over a 32-symbol
# alphabet — learnable to ~100% by both shapes in seconds of CPU
# training), the full-size target and the shallow/narrow draft each
# trained on it, and the serving workload drawn from the same
# distribution.  The measured acceptance rate is scraped from /metrics
# and recorded in the row — the speedup claim is "at THIS acceptance",
# not a universal constant; a workload the draft cannot predict
# self-disables via spec_min_accept_rate (tests pin that path).

#: the spec A/B language: alphabet size and the permutation seed
SPEC_LANG_MOD = 32
SPEC_LANG_SEED = 1234

#: target shape for the A/B: wide enough that decode steps (not HTTP/IPC
#: plumbing) dominate the closed-loop wall — at the default harness width
#: both engines saturate the request path and the A/B measures nothing
SPEC_TARGET_OVERRIDES = {"features_per_head": 64, "sequence_length": 96}

#: the draft: quarter width AND eighth depth (ROADMAP's
#: "shallow/quarter-width draft" — on an op-dispatch-bound CPU rig only
#: depth cuts per-step cost; on silicon the width cut is the byte-ratio
#: lever).  vocab_weight_factorization raised so the factorized embedding
#: keeps a non-degenerate intermediate at this width
SPEC_DRAFT_OVERRIDES = {"features_per_head": 16, "depth": 1,
                        "vocab_weight_factorization": 0.5,
                        "sequence_length": 96}

#: (steps, lr) phases per model (multi-phase supported — each phase
#: recompiles the step at its lr).  Measured: these budgets take both
#: models to ~1.0 argmax accuracy on the permutation language (half the
#: steps leaves the draft at ~0.79 and the A/B acceptance under water)
SPEC_TRAIN_PHASES = ((1400, 3e-3),)
SPEC_DRAFT_TRAIN_PHASES = ((3000, 3e-3),)

#: --spec request classes (prompt_tokens, max_tokens): longer responses
#: than WORKLOAD so the decode path, not per-request HTTP overhead, is
#: what the two engines differ on
SPEC_WORKLOAD = ((3, 80), (5, 48), (2, 88), (6, 32), (4, 64), (3, 88))


def _spec_perm():
    import numpy as np
    return np.random.default_rng(SPEC_LANG_SEED).permutation(SPEC_LANG_MOD)


def _spec_rows(perm, rng, n, seq):
    """``n`` on-manifold sequences: a random start symbol walking the
    permutation orbit."""
    import numpy as np
    rows = np.zeros((n, seq), np.int64)
    rows[:, 0] = rng.integers(0, len(perm), n)
    for t in range(1, seq):
        rows[:, t] = perm[rows[:, t - 1]]
    return rows.astype(np.int32)


def _train_bench_model(cfg_over, phases, perm, seed=0, bt=16):
    """Train one bench-scale model on the permutation language; returns
    (params, model, variables, final_loss)."""
    import numpy as np
    import jax.numpy as jnp
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer

    cfg = dict(BENCH_CONFIG)
    cfg.update(optimizer="adam-learning_rate", learning_rate=phases[0][1],
               warmup_steps=0, train_steps=10 ** 6, train_batch_size=bt,
               data_seed=seed)
    cfg.update(cfg_over)
    params = ModelParameter(cfg)
    model = Model(params)
    rng = np.random.default_rng(seed)
    seq = params.sequence_length

    def batch():
        rows = _spec_rows(perm, rng, bt, seq)
        return {"token_x": jnp.asarray(rows[:, :, None]),
                "token_y": jnp.asarray(np.roll(rows, -1, 1)[:, :, None])}

    trainer = Trainer(params, model)
    state = trainer.init_state(batch())
    metrics = {"loss": 0.0}
    for steps, lr in phases:
        params.learning_rate = lr
        # the jitted step bakes the learning rate as a trace-time constant
        # (optim/learning_rate.py); drop the cached step fn so each phase
        # actually recompiles at ITS lr — without this the anneal is a
        # silent no-op and phase 2 trains at phase 1's rate
        trainer._step_fn = None
        for _ in range(steps):
            state, metrics = trainer.step(state, batch())
    params.train = False
    variables = {k: jnp.asarray(v) for k, v in state.variables.items()}
    return params, model, variables, float(metrics["loss"])


def _build_spec_pair():
    """(target InterfaceWrapper, draft triple, alignment report): the
    trained full-width target + trained quarter-width draft the --spec A/B
    serves, with their measured teacher-forced argmax agreement."""
    import numpy as np
    import jax.numpy as jnp
    import time as _time
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.infer.interface import InterfaceWrapper
    from homebrewnlp_tpu.model import Model

    perm = _spec_perm()
    t0 = _time.monotonic()
    tparams, tmodel, tvars, tloss = _train_bench_model(
        dict(SPEC_TARGET_OVERRIDES,
             model_path="/tmp/bench_serving_spec_target"),
        SPEC_TRAIN_PHASES, perm, seed=0)
    dparams, dmodel, dvars, dloss = _train_bench_model(
        dict(SPEC_DRAFT_OVERRIDES,
             model_path="/tmp/bench_serving_spec_draft"),
        SPEC_DRAFT_TRAIN_PHASES, perm, seed=1)
    train_s = _time.monotonic() - t0

    # teacher-forced argmax agreement on fresh on-manifold rows — the
    # acceptance ceiling the serving run should approach
    rng = np.random.default_rng(99)
    rows = _spec_rows(perm, rng, 48, tparams.sequence_length)

    def preds(model, params, variables):
        from homebrewnlp_tpu.infer.interface import model_width_view
        out = []
        bt = 16
        pw, mw = model_width_view(params, model, bt)
        for lo in range(0, len(rows), bt):
            chunk = rows[lo:lo + bt]
            info = mw.apply(variables,
                            {"token_x": jnp.asarray(chunk[:, :, None]),
                             "token_y": jnp.asarray(chunk[:, :, None])})
            out.append(np.asarray(info.token_out.data,
                                  np.float32)[:, :, 0].argmax(-1))
        return np.concatenate(out)

    tp, dp = preds(tmodel, tparams, tvars), preds(dmodel, dparams, dvars)
    truth = np.roll(rows, -1, 1)
    gen = (slice(None), slice(1, -1))
    report = {
        "language": f"permutation map, {SPEC_LANG_MOD} symbols",
        "train_s": round(train_s, 1),
        "target_loss": round(tloss, 4), "draft_loss": round(dloss, 4),
        "target_accuracy": round(float((tp[gen] == truth[gen]).mean()), 4),
        "draft_accuracy": round(float((dp[gen] == truth[gen]).mean()), 4),
        "teacher_forced_agreement": round(float((tp[gen] == dp[gen]).mean()),
                                          4),
    }
    return (InterfaceWrapper(tparams, tmodel, tvars),
            (dparams, dmodel, dvars), report)


def _build_interface(config_path=None, latency=None):
    import numpy as np
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.infer.interface import InterfaceWrapper
    from homebrewnlp_tpu.model import Model
    import jax.numpy as jnp

    cfg = dict(BENCH_CONFIG)
    if config_path:
        with open(config_path) as f:
            cfg = {**json.load(f), "decode_loop": "stepped"}
    params = ModelParameter(cfg)
    params.train = False
    model = Model(params)
    seq = params.sequence_dim.size
    tps = params.token_patch_dim.size
    zeros = np.zeros((1, seq, tps), np.int32)
    variables = {k: jnp.asarray(v)
                 for k, v in model.init({"token_x": zeros,
                                         "token_y": zeros}).items()}
    interface = InterfaceWrapper(params, model, variables)
    if latency:
        from homebrewnlp_tpu.utils.fault_injection import FaultyInterface
        interface = FaultyInterface(interface, latency=latency)
    return interface


def _spawn(interface, engine: str, slots: int, batch: int, spec_k: int = 8,
           block_tokens: int = 8, trace_dir=None):
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.infer import rest_api

    # "spec" is the continuous engine with draft-and-verify required (the
    # caller attaches interface.draft); "paged" is the continuous engine on
    # the KV block pool with kv_paging required; "spec_paged" composes BOTH
    # components (the Engine's spec_paged_chunk_step); any construction
    # failure must fail the A/B loudly, not silently measure a lesser engine
    serve_engine = ("continuous" if engine in ("spec", "paged", "spec_paged")
                    else engine)
    trace_over = {}
    if trace_dir:
        # --trace: per-request span export (docs/OBSERVABILITY.md 'Request
        # tracing') under a scratch model_path, so the per-hop breakdown
        # never writes into a real run directory
        trace_over = {"trace_requests": True, "model_path": str(trace_dir)}
    params = ModelParameter(interface.params,
                            serve_engine=serve_engine, serve_slots=slots,
                            serve_batch_size=batch,
                            kv_paging="on" if engine in ("paged",
                                                         "spec_paged")
                            else "off",
                            kv_block_tokens=block_tokens,
                            spec_decode="draft" if engine in ("spec",
                                                              "spec_paged")
                            else "off",
                            spec_draft_tokens=spec_k, **trace_over)
    params.train = False
    # /health's decode_path reads the INTERFACE's params (FaultyInterface
    # proxies); the spec knobs themselves ride the resolved `params`
    interface.params.serve_engine = serve_engine
    interface.params.spec_decode = params.spec_decode
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(target=rest_api.serve, args=(params, interface),
                         kwargs={"port": port, "isolate": True, "stop": stop},
                         daemon=True, name="bench-server")
    t.start()
    return port, stop, t


def _post(port, payload, timeout=180.0, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/token_completion",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _wait_up(port, deadline_s=180.0):
    req = urllib.request.Request(f"http://127.0.0.1:{port}/health")
    t0 = time.monotonic()
    while True:
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read())
        except Exception:
            if time.monotonic() - t0 > deadline_s:
                raise
            time.sleep(0.25)


def _scrape_buckets(port):
    """Cumulative TTFT/ITL bucket counts from the /metrics exposition."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
        text = resp.read().decode()
    out = {}
    for name in ("hbnlp_serve_ttft_seconds", "hbnlp_serve_itl_seconds"):
        pat = re.compile(rf'^{name}_bucket{{le="([^"]+)"}} (\d+)', re.M)
        pairs = sorted(
            (float("inf") if le == "+Inf" else float(le), int(c))
            for le, c in pat.findall(text))
        bounds = [b for b, _ in pairs if b != float("inf")]
        cum = [c for _, c in pairs]
        out[name] = (bounds,
                     [c - (cum[i - 1] if i else 0)
                      for i, c in enumerate(cum)])
    return out


def _scrape_values(port, names):
    """Plain gauge/counter samples (``name value`` lines) from /metrics."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
        text = resp.read().decode()
    out = {}
    for name in names:
        m = re.search(rf"^{name} ([0-9.e+-]+)", text, re.M)
        out[name] = float(m.group(1)) if m else 0.0
    return out


def _scrape_spec(port):
    """The hbnlp_spec_* counters (cumulative) from /metrics."""
    v = _scrape_values(port, ("hbnlp_spec_drafted_tokens_total",
                              "hbnlp_spec_accepted_tokens_total",
                              "hbnlp_spec_state"))
    return {"drafted": v["hbnlp_spec_drafted_tokens_total"],
            "accepted": v["hbnlp_spec_accepted_tokens_total"],
            "state": v["hbnlp_spec_state"]}


def _quantiles(before, after):
    """p50/p99 of the TIMED window: per-bucket count delta between two
    scrapes — the warmup window's compile-dominated TTFTs must not ride
    the tail of the measured distribution."""
    from homebrewnlp_tpu.telemetry.registry import histogram_quantile
    out = {}
    for name, (bounds, counts_after) in after.items():
        counts_before = before.get(name, (bounds, [0] * len(counts_after)))[1]
        counts = [a - b for a, b in zip(counts_after, counts_before)]
        key = "ttft" if "ttft" in name else "itl"
        out[f"{key}_count"] = sum(counts)
        for q in (0.5, 0.99):
            out[f"{key}_p{int(q * 100)}"] = histogram_quantile(bounds,
                                                               counts, q)
    return out


class _Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.ok = 0
        self.errors = {}
        self.generated = 0

    def record(self, status, body, prompt_len):
        with self.lock:
            if status == 200:
                self.ok += 1
                self.generated += max(0, len(body.get("tokens", ()))
                                      - prompt_len)
            else:
                key = str(status)
                self.errors[key] = self.errors.get(key, 0) + 1


def _request_for(rng, i, orbit=None):
    classes = WORKLOAD if orbit is None else SPEC_WORKLOAD
    plen, mt = classes[i % len(classes)]
    if orbit is not None:
        # --spec A/B: on-manifold prompts (a walk of the trained
        # permutation language) so acceptance measures the aligned pair,
        # not out-of-distribution noise
        toks = [int(rng.integers(0, len(orbit)))]
        for _ in range(plen - 1):
            toks.append(int(orbit[toks[-1]]))
    else:
        toks = [int(x) for x in rng.integers(1, 255, plen)]
    return {"tokens": toks, "max_tokens": mt, "temperature": 0.0}, plen


def _closed_loop(port, rng, workers: int, per_worker: int, orbit=None,
                 trace_ids=None):
    stats = _Stats()
    # payloads pre-drawn on this thread: numpy Generators are not
    # thread-safe, and racy draw order would break --seed reproducibility
    payloads = [[_request_for(rng, w * per_worker + i, orbit=orbit)
                 for i in range(per_worker)] for w in range(workers)]

    def worker(w):
        from homebrewnlp_tpu.telemetry import tracectx
        for payload, plen in payloads[w]:
            headers = None
            if trace_ids is not None:
                # --trace: the CLIENT mints the id (header adoption at the
                # HTTP edge), so the per-hop files are findable afterwards
                tid = tracectx.new_trace_id()
                headers = {tracectx.TRACE_HEADER: tid}
            t_req = time.monotonic()
            try:
                status, body = _post(port, payload, headers=headers)
            except Exception:
                stats.record(599, {}, plen)
                continue
            stats.record(status, body, plen)
            if trace_ids is not None and status == 200:
                with stats.lock:
                    trace_ids.append((tid, time.monotonic() - t_req))

    t0 = time.monotonic()
    threads = [threading.Thread(target=worker, args=(w,), daemon=True,
                                name=f"bench-worker-{w}")
               for w in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    return stats, wall


def _open_loop(port, rng, rate_rps: float, duration_s: float, orbit=None):
    stats = _Stats()
    threads = []
    t0 = time.monotonic()
    i = 0
    while time.monotonic() - t0 < duration_s:
        payload, plen = _request_for(rng, i, orbit=orbit)
        i += 1

        def fire(payload=payload, plen=plen):
            try:
                status, body = _post(port, payload)
            except Exception:
                stats.record(599, {}, plen)
                return
            stats.record(status, body, plen)

        th = threading.Thread(target=fire, daemon=True,
                              name=f"bench-fire-{len(threads)}")
        th.start()
        threads.append(th)
        time.sleep(float(rng.exponential(1.0 / rate_rps)))
    for th in threads:
        th.join(timeout=180)
    wall = time.monotonic() - t0
    return stats, wall


def _hop_breakdown(trace_dir, trace_ids) -> dict:
    """p50/p99 per-hop seconds over the traced closed-loop requests:
    queue-wait / prefill / decode (+ kv-block-wait when paged), plus the
    client-measured dispatch overhead (client wall minus the in-engine
    request span).  Reads the per-request exports the tracer wrote under
    <trace_dir>/traces/.  The router-dispatch hop of a REPLICATED
    deployment lives in the router process's blackbox, not these files —
    merge it with ``scripts/forensics.py --trace <id>``."""
    import numpy as np
    per_hop: dict = {}
    dispatch_overhead = []
    found = 0
    for tid, wall in trace_ids:
        path = os.path.join(trace_dir, "traces", f"trace_{tid}.json")
        try:
            with open(path) as f:
                hops = json.load(f).get("hops") or {}
        except (OSError, ValueError):
            continue
        found += 1
        for key in ("queue_wait", "kv_block_wait", "prefill", "decode"):
            if key in hops:
                per_hop.setdefault(key, []).append(hops[key])
        if "request" in hops:
            dispatch_overhead.append(max(0.0, wall - hops["request"]))
    out = {"traced_requests": found}
    for key, vals in sorted(per_hop.items()):
        out[key] = {"p50": round(float(np.percentile(vals, 50)), 6),
                    "p99": round(float(np.percentile(vals, 99)), 6),
                    "n": len(vals)}
    if dispatch_overhead:
        out["dispatch"] = {
            "p50": round(float(np.percentile(dispatch_overhead, 50)), 6),
            "p99": round(float(np.percentile(dispatch_overhead, 99)), 6),
            "n": len(dispatch_overhead)}
    return out


def run_engine(engine: str, args, latency=None, spec_ctx=None) -> dict:
    import numpy as np
    orbit = None
    if spec_ctx is not None:
        interface, draft, orbit = (spec_ctx["interface"], spec_ctx["draft"],
                                   spec_ctx["orbit"])
        interface.draft = draft if engine == "spec" else None
    else:
        interface = _build_interface(args.config, latency=latency)
    trace_dir = None
    if getattr(args, "trace", False):
        import tempfile
        trace_dir = tempfile.mkdtemp(prefix=f"bench_trace_{engine}_")
    port, stop, t = _spawn(interface, engine, args.slots, args.batch,
                           spec_k=getattr(args, "spec_k", 8),
                           trace_dir=trace_dir)
    try:
        health = _wait_up(port)
        served = "continuous" if engine == "spec" else engine
        assert (health.get("engine") or {}).get("mode") == served, health
        if engine == "spec":
            spec_info = (health.get("engine") or {}).get("spec") or {}
            assert spec_info.get("enabled"), health
        # warmup: compile every program shape out of the timed window
        warm_rng = np.random.default_rng(7)
        for i in range(max(2, args.slots)):
            payload, _ = _request_for(warm_rng, i, orbit=orbit)
            _post(port, payload)
        # greedy bit-parity canary: the same request answers identically on
        # every engine (the --check gate compares across rows)
        canary, _ = _request_for(np.random.default_rng(1234), 3,
                                 orbit=orbit)
        canary_status, canary_body = _post(port, canary)
        rng = np.random.default_rng(args.seed)
        # the scrape merges the device loop's snapshot, published once per
        # loop turn — give it one idle poll to flush the warmup counts
        time.sleep(1.5)
        baseline = _scrape_buckets(port)
        spec_before = _scrape_spec(port) if engine == "spec" else None
        trace_ids = [] if trace_dir else None
        closed, closed_wall = _closed_loop(port, rng, args.concurrency,
                                           args.requests, orbit=orbit,
                                           trace_ids=trace_ids)
        open_stats, open_wall = _open_loop(port, rng, args.rate,
                                           args.duration, orbit=orbit)
        time.sleep(1.5)   # final snapshot publish
        q = _quantiles(baseline, _scrape_buckets(port))
        row = {
            "engine": engine,
            "canary": (canary_body.get("tokens")
                       if canary_status == 200 else None),
            "closed_loop": {
                "requests_ok": closed.ok, "errors": closed.errors,
                "generated_tokens": closed.generated,
                "wall_s": round(closed_wall, 3),
                "tokens_per_sec": round(closed.generated
                                        / max(closed_wall, 1e-9), 2),
            },
            "open_loop": {
                "rate_rps": args.rate, "requests_ok": open_stats.ok,
                "errors": open_stats.errors,
                "generated_tokens": open_stats.generated,
                "wall_s": round(open_wall, 3),
            },
            **{k: (round(v, 6) if isinstance(v, float) else v)
               for k, v in q.items()},
        }
        if engine == "spec":
            after = _scrape_spec(port)
            drafted = after["drafted"] - spec_before["drafted"]
            accepted = after["accepted"] - spec_before["accepted"]
            row["spec"] = {
                "drafted": int(drafted), "accepted": int(accepted),
                "accept_rate": round(accepted / max(drafted, 1.0), 4),
                "state": after["state"],
            }
        if trace_ids is not None:
            # per-hop latency anatomy of the closed-loop window (ISSUE 15
            # satellite): where a request's wall time actually went
            row["hops"] = _hop_breakdown(trace_dir, trace_ids)
            if engine == "batch" and not row["hops"]["traced_requests"]:
                # an explicit absence, not a zero that reads like a
                # collection failure
                row["hops"]["note"] = ("batch engine untraced — request "
                                       "tracing rides the continuous "
                                       "engine's hooks")
        return row
    finally:
        stop.set()
        t.join(timeout=30)


# ---- shared-prefix workload (--shared-prefix; docs/SERVING.md 'Paged KV') --
#
# The chat pattern paging + radix sharing exist for: every request opens
# with the same system prompt and diverges in a short tail.  The paged
# engine should (a) answer prefix-HIT requests with TTFT << a cold
# request's (prefill over the shared span is skipped — the blocks are
# referenced, not recomputed), (b) stay greedy-bit-identical to the plain
# continuous engine, and (c) show block occupancy tracking LIVE tokens,
# not slots x worst-case length.  TTFT is probed client-side with
# max_tokens=1 requests (end-to-end admission->first-token wall for the
# smallest possible decode), cold on a FRESH system prompt per trial, hit
# on tails diverging from an already-served one.

SHARED_SYS_TOKENS = 44          # shared system-prompt length (of seq 64)
SHARED_BLOCK_TOKENS = 4         # paging granularity for the workload
SHARED_TRIALS = 3
SHARED_HITS_PER_TRIAL = 3


def _shared_sysprompt(trial: int):
    import numpy as np
    rng = np.random.default_rng(1000 + trial)
    return [int(t) for t in rng.integers(1, 255, SHARED_SYS_TOKENS)]


def _timed_post(port, payload):
    t0 = time.monotonic()
    status, body = _post(port, payload)
    return time.monotonic() - t0, status, body


def run_shared_prefix(args) -> dict:
    import numpy as np
    interface = _build_interface(args.config)
    # greedy canary on the PLAIN continuous engine first: the paged
    # engine's answers must be bit-identical
    canary_payload = {"tokens": [3, 1, 4, 1, 5], "max_tokens": 8,
                     "temperature": 0.0}
    port, stop, t = _spawn(interface, "continuous", args.slots, args.batch)
    try:
        _wait_up(port)
        status, plain_canary = _post(port, canary_payload)
        assert status == 200, plain_canary
    finally:
        stop.set()
        t.join(timeout=30)
    port, stop, t = _spawn(interface, "paged", args.slots, args.batch,
                           block_tokens=SHARED_BLOCK_TOKENS)
    try:
        health = _wait_up(port)
        paging = (health.get("engine") or {}).get("paging") or {}
        assert paging.get("blocks_total"), health
        # warmup compiles every chunk-program shape out of the timed probes
        warm_rng = np.random.default_rng(7)
        for i in range(3):
            payload, _ = _request_for(warm_rng, i)
            _post(port, payload)
        status, paged_canary = _post(port, canary_payload)
        assert status == 200, paged_canary
        colds, hits = [], []
        for trial in range(SHARED_TRIALS):
            sysp = _shared_sysprompt(trial)
            dt, status, _ = _timed_post(
                port, {"tokens": sysp + [201, 202], "max_tokens": 1,
                       "temperature": 0.0})
            assert status == 200
            colds.append(dt)
            for j in range(SHARED_HITS_PER_TRIAL):
                dt, status, _ = _timed_post(
                    port, {"tokens": sysp + [210 + j], "max_tokens": 1,
                           "temperature": 0.0})
                assert status == 200
                hits.append(dt)
        time.sleep(1.5)  # device-loop snapshot publish
        kv = _scrape_values(port, (
            "hbnlp_kv_blocks_total", "hbnlp_kv_prefix_hit_tokens_total",
            "hbnlp_kv_prefix_hits_total", "hbnlp_kv_cow_copies_total"))
        # occupancy probe: sample the in-use gauge while long responses
        # decode — the live-token footprint, vs the slot engine's
        # slots x seq_blocks worst-case pinning
        peak = [0.0]
        done = threading.Event()

        def sample():
            while not done.is_set():
                try:
                    v = _scrape_values(port, ("hbnlp_kv_blocks_in_use",))
                    peak[0] = max(peak[0], v["hbnlp_kv_blocks_in_use"])
                except Exception:
                    pass
                time.sleep(0.15)

        sampler = threading.Thread(target=sample, daemon=True,
                                   name="bench-occupancy-sampler")
        sampler.start()
        occ_threads = [threading.Thread(
            target=_post, args=(port, {"tokens": [5 + i], "max_tokens": 16,
                                       "temperature": 0.0}), daemon=True,
            name=f"bench-occ-{i}")
            for i in range(args.slots)]
        for th in occ_threads:
            th.start()
        for th in occ_threads:
            th.join(timeout=180)
        time.sleep(1.6)  # one more scrape past the final chunk
        done.set()
        sampler.join(timeout=5)
        seq_blocks = 64 // SHARED_BLOCK_TOKENS  # BENCH_CONFIG sequence
        cold_med = sorted(colds)[len(colds) // 2]
        hit_med = sorted(hits)[len(hits) // 2]
        return {
            "mode": "shared_prefix",
            "sys_tokens": SHARED_SYS_TOKENS,
            "block_tokens": SHARED_BLOCK_TOKENS,
            "canary_parity": plain_canary.get("tokens")
            == paged_canary.get("tokens"),
            "cold_ttft_s": [round(v, 4) for v in colds],
            "hit_ttft_s": [round(v, 4) for v in hits],
            "cold_ttft_median_s": round(cold_med, 4),
            "hit_ttft_median_s": round(hit_med, 4),
            "hit_over_cold": round(hit_med / max(cold_med, 1e-9), 4),
            "prefix_hit_tokens": int(
                kv["hbnlp_kv_prefix_hit_tokens_total"]),
            "prefix_hits": int(kv["hbnlp_kv_prefix_hits_total"]),
            "occupancy": {
                "blocks_total": int(kv["hbnlp_kv_blocks_total"]),
                "peak_blocks_in_use": int(peak[0]),
                "slot_engine_equivalent_blocks": args.slots * seq_blocks,
            },
        }
    finally:
        stop.set()
        t.join(timeout=30)


# ---- composed spec-on-paged (--spec-paged; docs/SERVING.md 'Engine
# architecture') --------------------------------------------------------------
#
# The Engine's composition headline: spec-decode and paged KV were measured
# separately (the `spec` and `shared_prefix` rows) but refused to compose
# until the chunk-program registry made the carry composable
# (`spec_paged_chunk_step`).  This mode proves the win is MULTIPLICATIVE in
# ONE deployment: against the PLAIN continuous engine, the composed engine
# must deliver the draft-and-verify closed-loop tokens/sec speedup AND the
# prefix-hit TTFT collapse, while staying greedy-bit-identical.  Both the
# throughput window and the TTFT probes run against the SAME serving
# process — no per-feature deployments.

SPEC_PAGED_BLOCK_TOKENS = 8     # paging granularity (divides seq 96)
SPEC_PAGED_SYS_TOKENS = 64      # shared system-prompt length (8 full blocks)
SPEC_PAGED_TRIALS = 3
SPEC_PAGED_HITS_PER_TRIAL = 3


def _orbit_sysprompt(orbit, trial: int):
    """A shared system prompt ON the permutation manifold (an orbit walk
    from a per-trial start), so the composed deployment drafts at the
    trained pair's acceptance rate while the radix cache serves the shared
    span.  Distinct starts guarantee distinct first blocks (the radix key
    is the token sequence from the root), so each trial's first probe is
    genuinely cold."""
    toks = [(11 * trial + 5) % len(orbit)]
    for _ in range(SPEC_PAGED_SYS_TOKENS - 1):
        toks.append(int(orbit[toks[-1]]))
    return toks


def run_spec_paged(args) -> dict:
    import numpy as np
    interface, draft, align = _build_spec_pair()
    orbit = _spec_perm()
    canary_payload, _ = _request_for(np.random.default_rng(1234), 3,
                                     orbit=orbit)

    def warm_and_canary(port):
        warm_rng = np.random.default_rng(7)
        for i in range(max(2, args.slots)):
            payload, _ = _request_for(warm_rng, i, orbit=orbit)
            _post(port, payload)
        status, body = _post(port, canary_payload)
        assert status == 200, body
        return body

    # phase A: the PLAIN continuous engine — the baseline BOTH composed
    # components must beat together (draft detached so nothing drafts)
    interface.draft = None
    port, stop, t = _spawn(interface, "continuous", args.slots, args.batch)
    try:
        _wait_up(port)
        plain_canary = warm_and_canary(port)
        rng = np.random.default_rng(args.seed)
        plain_stats, plain_wall = _closed_loop(
            port, rng, args.concurrency, args.requests, orbit=orbit)
    finally:
        stop.set()
        t.join(timeout=30)

    # phase B: the composed spec_paged_chunk_step deployment
    interface.draft = draft
    port, stop, t = _spawn(interface, "spec_paged", args.slots, args.batch,
                           spec_k=args.spec_k,
                           block_tokens=SPEC_PAGED_BLOCK_TOKENS)
    try:
        health = _wait_up(port)
        einfo = health.get("engine") or {}
        # the composed deployment must BE the composed program — a
        # component-wise fallback here would silently measure a lesser
        # engine and void the row
        assert einfo.get("program") == "spec_paged_chunk_step", health
        assert (einfo.get("spec") or {}).get("enabled"), health
        assert (einfo.get("paging") or {}).get("blocks_total"), health
        comp_canary = warm_and_canary(port)
        time.sleep(1.5)  # device-loop snapshot publish
        spec_before = _scrape_spec(port)
        rng = np.random.default_rng(args.seed)
        comp_stats, comp_wall = _closed_loop(
            port, rng, args.concurrency, args.requests, orbit=orbit)
        # prefix-hit vs cold TTFT in the SAME deployment: a fresh shared
        # system prompt is cold; tails diverging off it hit its promoted
        # blocks.  Closed-loop prompts (2-6 tokens) never fill a block, so
        # they cannot pre-warm the probes.
        colds, hits = [], []
        for trial in range(SPEC_PAGED_TRIALS):
            sysp = _orbit_sysprompt(orbit, trial)
            nxt = int(orbit[sysp[-1]])   # the on-manifold next symbol
            dt, status, _ = _timed_post(
                port, {"tokens": sysp + [(nxt + 11) % len(orbit)],
                       "max_tokens": 1, "temperature": 0.0})
            assert status == 200
            colds.append(dt)
            for j in range(SPEC_PAGED_HITS_PER_TRIAL):
                dt, status, _ = _timed_post(
                    port, {"tokens": sysp + [(nxt + 1 + j) % len(orbit)],
                           "max_tokens": 1, "temperature": 0.0})
                assert status == 200
                hits.append(dt)
        time.sleep(1.5)  # device-loop snapshot publish
        spec_after = _scrape_spec(port)
        kv = _scrape_values(port, (
            "hbnlp_kv_blocks_total", "hbnlp_kv_prefix_hit_tokens_total",
            "hbnlp_kv_prefix_hits_total"))
    finally:
        stop.set()
        t.join(timeout=30)

    plain_tps = plain_stats.generated / max(plain_wall, 1e-9)
    comp_tps = comp_stats.generated / max(comp_wall, 1e-9)
    drafted = spec_after["drafted"] - spec_before["drafted"]
    accepted = spec_after["accepted"] - spec_before["accepted"]
    cold_med = sorted(colds)[len(colds) // 2]
    hit_med = sorted(hits)[len(hits) // 2]
    return {
        "mode": "spec_paged",
        "program": "spec_paged_chunk_step",
        "alignment": align,
        "spec_k": args.spec_k,
        "block_tokens": SPEC_PAGED_BLOCK_TOKENS,
        "sys_tokens": SPEC_PAGED_SYS_TOKENS,
        "canary_parity": (plain_canary.get("tokens")
                          == comp_canary.get("tokens")),
        "plain": {
            "requests_ok": plain_stats.ok, "errors": plain_stats.errors,
            "generated_tokens": plain_stats.generated,
            "wall_s": round(plain_wall, 3),
            "tokens_per_sec": round(plain_tps, 2),
        },
        "composed": {
            "requests_ok": comp_stats.ok, "errors": comp_stats.errors,
            "generated_tokens": comp_stats.generated,
            "wall_s": round(comp_wall, 3),
            "tokens_per_sec": round(comp_tps, 2),
        },
        "tokens_per_sec_speedup": round(comp_tps / max(plain_tps, 1e-9), 3),
        "spec": {
            "drafted": int(drafted), "accepted": int(accepted),
            "accept_rate": round(accepted / max(drafted, 1.0), 4),
            "state": spec_after["state"],
        },
        "cold_ttft_s": [round(v, 4) for v in colds],
        "hit_ttft_s": [round(v, 4) for v in hits],
        "cold_ttft_median_s": round(cold_med, 4),
        "hit_ttft_median_s": round(hit_med, 4),
        "hit_over_cold": round(hit_med / max(cold_med, 1e-9), 4),
        "prefix_hit_tokens": int(kv["hbnlp_kv_prefix_hit_tokens_total"]),
        "prefix_hits": int(kv["hbnlp_kv_prefix_hits_total"]),
        "blocks_total": int(kv["hbnlp_kv_blocks_total"]),
    }


# ---- multi-replica tier (--replicas N; docs/SERVING.md) ---------------------
#
# Aggregate tokens/sec should scale ~linearly in replicas.  This rig has
# ONE host core (the PR 10 bench_multihost caveat), so N real CPU-decoding
# replicas serialize on compute and CANNOT scale in wall time on this box
# — the committed curve therefore measures the TIER (router dispatch, per-
# replica serving stacks, IPC) with each replica's decode emulated as a
# DEVICE WAIT (a fixed sleep per decode call, the time a real accelerator
# would spend off-CPU), plus an honest real-model 1->2 datapoint with the
# rig caveat recorded.  On silicon the re-measure drops the emulation
# (queued on the tunnel like every prior row).

#: replica-bench model: tiny (compile + decode cost << the device wait)
REPLICA_OVERRIDES = {"sequence_length": 16, "features_per_head": 8,
                     "heads": 2, "depth": 1, "vocab_size": 64,
                     "serve_engine": "batch", "serve_batch_size": 4}
#: short requests (prompt, max_tokens) — each ~1 decode call
REPLICA_WORKLOAD = ((2, 4), (3, 6), (2, 8))
#: emulated device seconds per decode call
REPLICA_DEVICE_WAIT_S = 0.4


class _WaitInterface:
    """Device-wait emulation: every decode call sleeps ``wait_s`` first —
    the off-CPU accelerator time a CPU rig cannot reproduce.  Unlike
    FaultyInterface's per-index latency schedules this waits on EVERY
    call (a uniform device, not an injected stall)."""

    def __init__(self, inner, wait_s: float):
        self._inner = inner
        self._wait = float(wait_s)

    def complete_tokens(self, *a, **kw):
        time.sleep(self._wait)
        return self._inner.complete_tokens(*a, **kw)

    def complete_tokens_batch(self, *a, **kw):
        time.sleep(self._wait)
        return self._inner.complete_tokens_batch(*a, **kw)

    def complete(self, *a, **kw):
        time.sleep(self._wait)
        return self._inner.complete(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _replica_bench_main(cfg, port, index):
    """Replica subprocess body (spawn target — module-level so the spawn
    context can re-import it): build the bench interface, serve one
    isolated deployment, optionally under the device-wait emulation."""
    cfg = dict(cfg)
    wait = float(cfg.pop("_bench_wait_s", 0.0) or 0.0)
    import numpy as np
    import jax.numpy as jnp
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.distributed.replica_fleet import install_replica_stop
    from homebrewnlp_tpu.infer.interface import InterfaceWrapper
    from homebrewnlp_tpu.infer.rest_api import serve
    from homebrewnlp_tpu.model import Model

    stop = install_replica_stop()
    params = ModelParameter(cfg)
    params.train = False
    model = Model(params)
    seq = params.sequence_dim.size
    tps = params.token_patch_dim.size
    zeros = np.zeros((1, seq, tps), np.int32)
    variables = {k: jnp.asarray(v)
                 for k, v in model.init({"token_x": zeros,
                                         "token_y": zeros}).items()}
    interface = InterfaceWrapper(params, model, variables)
    if wait:
        interface = _WaitInterface(interface, wait)
    print(f"[replica {index}] bench replica on :{port}", flush=True)
    serve(params, interface, port=port, isolate=True, stop=stop)


def _replica_request(rng, i):
    plen, mt = REPLICA_WORKLOAD[i % len(REPLICA_WORKLOAD)]
    toks = [int(x) for x in rng.integers(1, 63, plen)]
    return {"tokens": toks, "max_tokens": mt, "temperature": 0.0}, plen


def _run_replica_point(n: int, wait_s: float, args) -> dict:
    """One point of the scaling curve: n replicas + router, closed loop."""
    import numpy as np
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.distributed.replica_fleet import ReplicaFleet
    from homebrewnlp_tpu.infer import rest_api
    from homebrewnlp_tpu.infer.router import Replica, Router
    from homebrewnlp_tpu.infer.serving_guard import HTTPStatusError

    cfg = {**BENCH_CONFIG, **REPLICA_OVERRIDES,
           "model_path": "/tmp/bench_serving_replica",
           "_bench_wait_s": wait_s}
    params = ModelParameter({k: v for k, v in cfg.items()
                             if not k.startswith("_")})
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        router_port = s.getsockname()[1]
    base = router_port + 1
    fleet = ReplicaFleet(params, n, base_port=base,
                         target=_replica_bench_main)
    fleet.cfg = dict(cfg)  # ride the bench-only _bench_wait_s key through
    router = Router([Replica(i, base + i) for i in range(n)],
                    affinity_tokens=0,  # pure least-loaded: the scaling
                    forward_timeout_s=300.0)  # curve, not cache locality

    def dispatch(path, body):
        if path == "/health":
            return router.health()
        if path == "/metrics":
            return {"_prometheus": router.metrics()}
        return router.forward(path, body)

    try:
        # non-daemonic replicas: start() under the finally that stops them
        fleet.start()
        threading.Thread(
            target=rest_api._run_http, name="bench-router-http",
            args=(router_port, ["/token_completion", "/health", "/metrics"],
                  dispatch, 1), daemon=True).start()
        deadline = time.monotonic() + 600
        while True:
            try:
                h = _wait_up(router_port, deadline_s=30)
                if all("health" in r for r in h.get("replicas", ())):
                    break
            except Exception:
                pass
            if time.monotonic() > deadline:
                raise TimeoutError("replica fleet never came up")
            time.sleep(1.0)
        # warmup: compile every replica's decode programs off the clock
        warm_rng = np.random.default_rng(7)
        for round_ in range(2):
            threads = []
            for i in range(n * 2):
                payload, _ = _replica_request(warm_rng, i)
                th = threading.Thread(target=_post,
                                      args=(router_port, payload),
                                      daemon=True,
                                      name=f"bench-warm-{i}")
                th.start()
                threads.append(th)
            for th in threads:
                th.join(timeout=300)
        stats = _Stats()
        rng = np.random.default_rng(args.seed)
        workers = max(2, 3 * n)
        per_worker = args.requests
        payloads = [[_replica_request(rng, w * per_worker + i)
                     for i in range(per_worker)] for w in range(workers)]

        def worker(w):
            for payload, plen in payloads[w]:
                try:
                    status, body = _post(router_port, payload, timeout=300)
                except Exception:
                    stats.record(599, {}, plen)
                    continue
                stats.record(status, body, plen)

        t0 = time.monotonic()
        threads = [threading.Thread(target=worker, args=(w,), daemon=True,
                                    name=f"bench-worker-{w}")
                   for w in range(workers)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.monotonic() - t0
        return {"replicas": n, "requests_ok": stats.ok,
                "errors": stats.errors,
                "generated_tokens": stats.generated,
                "wall_s": round(wall, 3),
                "tokens_per_sec": round(stats.generated / max(wall, 1e-9),
                                        2),
                "workers": workers}
    finally:
        fleet.stop()


def run_replicas(args) -> dict:
    """The scaling sweep: 1 -> args.replicas doubling, device-wait
    emulated; plus a real-model 1->2 honesty datapoint."""
    ns = [1]
    while ns[-1] * 2 <= args.replicas:
        ns.append(ns[-1] * 2)
    if ns[-1] != args.replicas:
        ns.append(args.replicas)
    curve = []
    for n in ns:
        row = _run_replica_point(n, REPLICA_DEVICE_WAIT_S, args)
        print(json.dumps({"replica_point": row}), flush=True)
        curve.append(row)
    base = curve[0]["tokens_per_sec"]
    for row in curve:
        row["scaling_efficiency"] = round(
            row["tokens_per_sec"] / max(base * row["replicas"], 1e-9), 3)
    real = []
    for n in (1, 2):
        row = _run_replica_point(n, 0.0, args)
        print(json.dumps({"replica_real_point": row}), flush=True)
        real.append(row)
    real_base = real[0]["tokens_per_sec"]
    for row in real:
        row["scaling_efficiency"] = round(
            row["tokens_per_sec"] / max(real_base * row["replicas"], 1e-9),
            3)
    return {
        "mode": "replicas",
        "device_wait_s": REPLICA_DEVICE_WAIT_S,
        "host_cores": os.cpu_count(),
        "note": ("device-wait emulation: each decode call sleeps "
                 "device_wait_s (off-CPU accelerator time); this rig has "
                 f"{os.cpu_count()} host core(s), so real CPU decode "
                 "serializes across replicas — the 'real' rows record "
                 "that honestly, the emulated curve measures the tier; "
                 "silicon re-measure queued on the tunnel"),
        "curve": curve,
        "real_model": real,
    }


# ---- disaggregated prefill/decode tier (--disagg; docs/SERVING.md
# 'Disaggregated tier') --------------------------------------------------------
#
# The ISSUE 19 headline: at EQUAL replica count, a prefill:1,decode:2 class
# tier (router-resident global prefix index + KV-block streaming between
# replicas) against today's symmetric 3-replica tier, on the mixed workload
# disaggregation exists for — warm-session probes (long shared prefix, one
# output token: the TTFT population), long-decode requests (the throughput
# carriers), and cold new sessions arriving mid-window (the interference).
# Every session prompt opens with the SAME 32-token system head (the chat
# regime), which is exactly the affinity map's blind spot: its key is the
# first `serve_affinity_tokens`=32 tokens, so every family collides on one
# key and overload spills re-learn the key elsewhere — each spill turns the
# next probe of EVERY family into a duplicate cold prefill.  The global
# index keys on whole-block prefixes longest-first, so families stay
# distinct and warm requests route to (or migrate to) the replica that
# already holds their blocks.
#
# One-core rig: like --replicas, real CPU decode serializes across replica
# processes, so each replica emulates a COMPUTE-BOUND device — every
# dispatch sleeps `wait * tokens_advanced` (prefill chunks cost their token
# count, prefix-hit admissions cost only the divergent tail, idle dispatches
# cost nothing).  Sleeps overlap across processes, so the tier topology —
# not the single host core — sets the wall time.  Silicon re-measure queued
# on the tunnel like every prior row.

DISAGG_CLASSES = ("prefill", "decode", "decode")
DISAGG_BLOCK_TOKENS = 8
DISAGG_SHARED_HEAD = 32      # shared system head == default affinity_tokens
DISAGG_PREFIX_TOKENS = 64    # whole session prefix (8 full blocks)
DISAGG_FAMILIES = 4          # warm session families
DISAGG_HITS_PER_FAMILY = 10  # timed warm probes per family (TTFT samples)
DISAGG_DECODE_HEAVY = 12     # short-prompt long-decode requests
DISAGG_NEWCOMERS = 4         # cold sessions arriving inside the window
DISAGG_TOKEN_WAIT_S = 0.01   # emulated device seconds per token processed
DISAGG_OVERRIDES = {
    "sequence_length": 96, "serve_engine": "continuous", "kv_paging": "on",
    "kv_block_tokens": DISAGG_BLOCK_TOKENS, "kv_pool_blocks": 144,
    "serve_prefill_chunk_tokens": 8, "decode_chunk_tokens": 4,
    "trace_requests": True,
}


def _disagg_prefix(family: int):
    """Session prompt: the shared 32-token system head + a 32-token
    family-specific history (8 full blocks total)."""
    import numpy as np
    head = [((7 * i) % 251) + 1 for i in range(DISAGG_SHARED_HEAD)]
    rng = np.random.default_rng(5000 + family)
    tail = [int(x) for x in rng.integers(
        1, 255, DISAGG_PREFIX_TOKENS - DISAGG_SHARED_HEAD)]
    return head + tail


def _disagg_replica_main(cfg, port, index):
    """Replica subprocess body for the --disagg tiers: paged serving stack
    with the per-replica blackbox tag and the compute-bound device
    emulation (sleep per token each dispatch actually advanced)."""
    cfg = dict(cfg)
    wait = float(cfg.pop("_bench_tok_wait_s", 0.0) or 0.0)
    import numpy as np
    import jax.numpy as jnp
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.distributed.replica_fleet import install_replica_stop
    from homebrewnlp_tpu.infer.interface import InterfaceWrapper
    from homebrewnlp_tpu.infer.rest_api import serve
    from homebrewnlp_tpu.model import Model

    stop = install_replica_stop()
    params = ModelParameter(cfg)
    params.train = False
    if getattr(params, "trace_requests", False) and params.model_path:
        # replica-indexed blackbox tag BEFORE serve() (same discipline as
        # replica_fleet._replica_main) so forensics can merge the tier
        from homebrewnlp_tpu.telemetry import events as _flight
        _flight.configure(params.model_path, f"r{index}")
    if wait:
        from homebrewnlp_tpu.infer import paged as _paged
        _orig = _paged.PagedEngineExecutor.dispatch

        def _paced(self, steps, _orig=_orig):
            before = self.q.copy()
            out = _orig(self, steps)
            adv = float(np.clip(np.asarray(out) - before, 0, None).sum())
            if adv:
                time.sleep(wait * adv)
            return out

        _paged.PagedEngineExecutor.dispatch = _paced
    model = Model(params)
    seq = params.sequence_dim.size
    tps = params.token_patch_dim.size
    zeros = np.zeros((1, seq, tps), np.int32)
    variables = {k: jnp.asarray(v)
                 for k, v in model.init({"token_x": zeros,
                                         "token_y": zeros}).items()}
    interface = InterfaceWrapper(params, model, variables)
    print(f"[replica {index}] disagg bench replica "
          f"({cfg.get('serve_replica_class') or 'symmetric'}) on :{port}",
          flush=True)
    serve(params, interface, port=port, isolate=True, stop=stop)


def _load_forensics():
    """scripts/forensics.py as a module (the --trace merge helpers)."""
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "forensics.py")
    spec = importlib.util.spec_from_file_location("_bench_forensics", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _scrape_labeled(port, name):
    """{label_suffix: value} for one labeled series on /metrics."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
        text = resp.read().decode()
    out = {}
    for labels, val in re.findall(rf'^{name}{{([^}}]*)}} ([0-9.e+-]+)',
                                  text, re.M):
        out[labels] = out.get(labels, 0.0) + float(val)
    return out


def _disagg_timed_requests(args):
    """The seeded mixed workload: (kind, payload) list, shuffled."""
    import numpy as np
    reqs = []
    for f in range(DISAGG_FAMILIES):
        for j in range(DISAGG_HITS_PER_FAMILY):
            reqs.append(("probe", {"tokens": _disagg_prefix(f) + [30 + j],
                                   "max_tokens": 1, "temperature": 0.0}))
    # the held-back family (warmed cold-only, never re-probed in the warm
    # phase) migrates INSIDE the timed window, so the kv_transfer hop
    # rides a traced request into the merged per-hop rows
    for j in range(3):
        reqs.append(("probe", {"tokens": _disagg_prefix(DISAGG_FAMILIES)
                               + [70 + j],
                               "max_tokens": 1, "temperature": 0.0}))
    for i in range(DISAGG_DECODE_HEAVY):
        rng = np.random.default_rng(7000 + i)
        toks = [int(x) for x in rng.integers(1, 255, 4)]
        reqs.append(("decode", {"tokens": toks, "max_tokens": 32,
                                "temperature": 0.0}))
    for k in range(DISAGG_NEWCOMERS):
        reqs.append(("cold", {"tokens": _disagg_prefix(50 + k) + [9],
                              "max_tokens": 4, "temperature": 0.0}))
    order = np.random.default_rng(args.seed).permutation(len(reqs))
    return [reqs[i] for i in order]


def _run_disagg_tier(label: str, classes, args, wait_s: float) -> dict:
    """One tier (class topology or symmetric) end to end: real fleet +
    in-process router, warm/migrate phase, timed closed loop, merged
    per-hop trace rows."""
    import tempfile
    import numpy as np
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.distributed.replica_fleet import ReplicaFleet
    from homebrewnlp_tpu.infer import rest_api
    from homebrewnlp_tpu.infer.router import Replica, Router
    from homebrewnlp_tpu.telemetry import events as flight
    from homebrewnlp_tpu.telemetry import tracectx

    scratch = tempfile.mkdtemp(prefix=f"bench_disagg_{label}_")
    n = len(DISAGG_CLASSES)
    cfg = {**BENCH_CONFIG, **DISAGG_OVERRIDES, "serve_slots": args.slots,
           "model_path": scratch, "_bench_tok_wait_s": wait_s}
    params = ModelParameter({k: v for k, v in cfg.items()
                             if not k.startswith("_")})
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        router_port = s.getsockname()[1]
    base = router_port + 1
    fleet = ReplicaFleet(params, n, base_port=base,
                         target=_disagg_replica_main,
                         classes=list(classes) if classes else None)
    fleet.cfg = dict(cfg)  # ride the bench-only _bench_tok_wait_s through
    # the router IS this process: its blackbox (kv_transfer +
    # router/forward spans) lands next to the replicas' for the merge
    flight.recorder().clear()
    flight.configure(scratch, "router")
    router = Router([Replica(i, base + i) for i in range(n)],
                    forward_timeout_s=300.0, trace_requests=True,
                    classes=list(classes) if classes else None,
                    block_tokens=DISAGG_BLOCK_TOKENS,
                    kv_transfer_timeout_s=120.0)

    def dispatch(path, body, headers=None):
        if path == "/health":
            return router.health()
        if path == "/metrics":
            return {"_prometheus": router.metrics()}
        return router.forward(path, body, headers)

    def fire(payload, tid=None, timeout=600.0):
        headers = {tracectx.TRACE_HEADER: tid} if tid else None
        return _post(router_port, payload, timeout=timeout, headers=headers)

    def fire_all(payloads):
        threads = [threading.Thread(target=fire, args=(p,), daemon=True,
                                    name=f"bench-fire-{j}")
                   for j, p in enumerate(payloads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)

    canary_payload = {"tokens": _disagg_prefix(0) + [200], "max_tokens": 8,
                      "temperature": 0.0}
    results = []
    lock = threading.Lock()
    try:
        fleet.start()
        threading.Thread(
            target=rest_api._run_http, name="bench-disagg-router-http",
            args=(router_port,
                  ["/token_completion", "/health", "/metrics"],
                  dispatch, max(8, args.concurrency)), daemon=True).start()
        deadline = time.monotonic() + 900
        while True:
            try:
                h = _wait_up(router_port, deadline_s=30)
                if all("health" in r for r in h.get("replicas", ())):
                    break
            except Exception:
                pass
            if time.monotonic() > deadline:
                raise TimeoutError(f"{label} tier never came up")
            time.sleep(1.0)
        # compile warm, spread over the tier (in the class tier these are
        # short decodes -> the decode replicas; the prefill replica
        # compiles on the first session cold below)
        fire_all([{"tokens": [21 + i, 22, 23, 24], "max_tokens": 4,
                   "temperature": 0.0} for i in range(2 * n)])
        # session colds, sequential: exactly one cold prefill per family
        # (the +1 held-back family is warmed cold-only — its migration
        # happens inside the timed window, carrying a traced kv_transfer
        # span into the merged per-hop rows)
        for f in range(DISAGG_FAMILIES + 1):
            status, body = fire({"tokens": _disagg_prefix(f) + [9],
                                 "max_tokens": 1, "temperature": 0.0})
            assert status == 200, body
        # greedy canary, pass 1 (class tier: triggers family-0's
        # block migration to a decode replica)
        status, canary_a = fire(canary_payload)
        assert status == 200, canary_a
        # concurrent re-probes: the class tier migrates the remaining
        # families' blocks to decode replicas; the symmetric tier warms
        # its affinity map
        fire_all([{"tokens": _disagg_prefix(f) + [8], "max_tokens": 1,
                   "temperature": 0.0} for f in range(DISAGG_FAMILIES)])
        # greedy canary, pass 2 (class tier: answered by a decode-class
        # replica from the STREAMED blocks) — must match pass 1 bit-exact
        status, canary_b = fire(canary_payload)
        assert status == 200, canary_b

        shuffled = _disagg_timed_requests(args)
        workers = max(2, args.concurrency)

        def worker(w):
            for kind, payload in shuffled[w::workers]:
                tid = tracectx.new_trace_id()
                t_req = time.monotonic()
                try:
                    status, body = fire(payload, tid=tid)
                except Exception:
                    status, body = 599, {}
                wall = time.monotonic() - t_req
                gen = max(0, len(body.get("tokens", ()))
                          - len(payload["tokens"])) if status == 200 else 0
                with lock:
                    results.append((kind, wall, status, gen, tid))

        t0 = time.monotonic()
        threads = [threading.Thread(target=worker, args=(w,), daemon=True,
                                    name=f"bench-worker-{w}")
                   for w in range(workers)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.monotonic() - t0
        transfer = {
            "migrations": _scrape_labeled(router_port,
                                          "hbnlp_disagg_migrations_total"),
            "index": _scrape_labeled(router_port,
                                     "hbnlp_disagg_index_total"),
            "transfer_bytes": _scrape_values(
                router_port,
                ("hbnlp_disagg_transfer_bytes_total",))
            ["hbnlp_disagg_transfer_bytes_total"],
        }
        flight.flush(reason=f"bench-disagg-{label}")
    finally:
        fleet.stop()

    # merged per-hop rows (forensics --trace form): router blackbox
    # (router/forward + kv_transfer spans) + replica blackboxes + the
    # replicas' per-request trace exports, all under one scratch dir
    fz = _load_forensics()
    files = fz.load_files(fz.discover(scratch))
    per_hop, traced = {}, 0
    for kind, wall_r, status, gen, tid in results:
        rep = fz.trace_report(files, tid, scratch)
        hops = dict(rep["hops"])
        for k, v in ((rep.get("exported") or {}).get("hops") or {}).items():
            hops.setdefault(k, v)
        if hops:
            traced += 1
        for k, v in hops.items():
            per_hop.setdefault(k, []).append(v)
    hops_row = {"traced_requests": traced}
    for k, vals in sorted(per_hop.items()):
        hops_row[k] = {"p50": round(float(np.percentile(vals, 50)), 6),
                       "p99": round(float(np.percentile(vals, 99)), 6),
                       "n": len(vals)}

    errors = {}
    for kind, wall_r, status, gen, tid in results:
        if status != 200:
            errors[str(status)] = errors.get(str(status), 0) + 1
    ttfts = sorted(w for kind, w, status, gen, tid in results
                   if kind == "probe" and status == 200)
    gen_total = sum(gen for _, _, status, gen, _ in results if status == 200)
    return {
        "classes": ",".join(classes) if classes else "symmetric",
        "requests_ok": sum(1 for r in results if r[2] == 200),
        "errors": errors,
        "generated_tokens": gen_total,
        "wall_s": round(wall, 3),
        "tokens_per_sec": round(gen_total / max(wall, 1e-9), 2),
        "ttft_p50": round(float(np.percentile(ttfts, 50)), 4) if ttfts
        else None,
        "ttft_p99": round(float(np.percentile(ttfts, 99)), 4) if ttfts
        else None,
        "ttft_samples": len(ttfts),
        "canary": (canary_a.get("tokens"), canary_b.get("tokens")),
        "hops": hops_row,
        "transfer": transfer,
    }


def run_disagg(args) -> dict:
    sym = _run_disagg_tier("symmetric", None, args, DISAGG_TOKEN_WAIT_S)
    print(json.dumps({"disagg_symmetric_tier": sym}), flush=True)
    dis = _run_disagg_tier("classes", DISAGG_CLASSES, args,
                           DISAGG_TOKEN_WAIT_S)
    print(json.dumps({"disagg_class_tier": dis}), flush=True)
    canaries = [sym["canary"][0], sym["canary"][1],
                dis["canary"][0], dis["canary"][1]]
    parity = all(c == canaries[0] and c is not None for c in canaries)
    sym_row = {k: v for k, v in sym.items() if k != "canary"}
    dis_row = {k: v for k, v in dis.items() if k != "canary"}
    return {
        "mode": "disagg",
        "replicas": len(DISAGG_CLASSES),
        "device_token_wait_s": DISAGG_TOKEN_WAIT_S,
        "host_cores": os.cpu_count(),
        "note": ("compute-bound device emulation (sleep per token each "
                 "dispatch advanced) like the replicas row — the tier "
                 "topology, not the single host core, sets wall time; "
                 "every session prompt shares a 32-token system head, the "
                 "regime where the symmetric tier's affinity key "
                 "collides and overload spills duplicate cold prefills "
                 "while the global prefix index stays block-exact; "
                 "silicon re-measure queued on the tunnel"),
        "workload": {
            "families": DISAGG_FAMILIES,
            "prefix_tokens": DISAGG_PREFIX_TOKENS,
            "shared_head_tokens": DISAGG_SHARED_HEAD,
            "hit_probes": DISAGG_FAMILIES * DISAGG_HITS_PER_FAMILY,
            "in_window_migration_probes": 3,
            "decode_heavy": DISAGG_DECODE_HEAVY,
            "cold_newcomers": DISAGG_NEWCOMERS,
        },
        "canary_parity": parity,
        "symmetric": sym_row,
        "disagg": dis_row,
        "tokens_per_sec_ratio": round(
            dis["tokens_per_sec"] / max(sym["tokens_per_sec"], 1e-9), 3),
        "ttft_p99_ratio": round(
            (dis["ttft_p99"] or 1e9) / max(sym["ttft_p99"] or 1e-9, 1e-9),
            3),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--engines", default="batch,continuous",
                    help="comma list: batch, continuous")
    ap.add_argument("--slots", type=int, default=4,
                    help="serve_slots for the continuous engine")
    ap.add_argument("--batch", type=int, default=4,
                    help="serve_batch_size for the batch engine (kept equal "
                         "to --slots by default for a fair width match)")
    ap.add_argument("--concurrency", type=int, default=8,
                    help="closed-loop worker count")
    ap.add_argument("--requests", type=int, default=6,
                    help="closed-loop requests per worker")
    ap.add_argument("--rate", type=float, default=4.0,
                    help="open-loop arrival rate (req/s)")
    ap.add_argument("--duration", type=float, default=8.0,
                    help="open-loop duration (s)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--config", default=None,
                    help="config JSON instead of the harness-scale model")
    ap.add_argument("--latency", default=None,
                    help="FaultyInterface schedule 'I:SEC[,I:SEC...]' — "
                         "decode call I sleeps SEC (batch-path decode calls)")
    ap.add_argument("--out", default="BENCH_SERVING.json")
    ap.add_argument("--spec", action="store_true",
                    help="speculative A/B: train the aligned target/draft "
                         "pair, run continuous vs spec on the permutation "
                         "workload, record acceptance (docs/SERVING.md)")
    ap.add_argument("--shared-prefix", action="store_true",
                    dest="shared_prefix",
                    help="paged-KV shared-prefix workload: common system "
                         "prompt + divergent tails; records prefix-hit vs "
                         "cold TTFT, greedy parity vs the plain engine, "
                         "and block occupancy (docs/SERVING.md 'Paged KV')")
    ap.add_argument("--spec-paged", action="store_true", dest="spec_paged",
                    help="composed spec-on-paged deployment "
                         "(spec_paged_chunk_step) vs the plain continuous "
                         "engine: closed-loop draft-and-verify speedup AND "
                         "prefix-hit vs cold TTFT in the SAME serving "
                         "process, at greedy bit-parity (docs/SERVING.md "
                         "'Engine architecture')")
    ap.add_argument("--disagg", action="store_true",
                    help="disaggregated prefill/decode tier A/B: "
                         "prefill:1,decode:2 classes (KV-block streaming + "
                         "router global prefix index) vs the symmetric "
                         "3-replica tier at equal count, on a mixed "
                         "long-prefill/long-decode workload; records "
                         "aggregate tokens/sec, p99 TTFT, and merged "
                         "per-hop rows including the kv_transfer hop "
                         "(docs/SERVING.md 'Disaggregated tier')")
    ap.add_argument("--replicas", type=int, default=0,
                    help="multi-replica tier scaling sweep up to N "
                         "replicas behind the router (device-wait "
                         "emulation + real-model honesty rows; "
                         "docs/SERVING.md)")
    ap.add_argument("--spec-k", type=int, default=16, dest="spec_k",
                    help="spec_draft_tokens for the spec engine (verify "
                         "width k+1; tokens per round scale with it at "
                         "high acceptance — measured 1.5x at k=12, 2.0x "
                         "at k=16 on the CPU rig)")
    ap.add_argument("--trace", action="store_true",
                    help="enable request tracing on the served deployment "
                         "and record a p50/p99 per-hop breakdown "
                         "(queue-wait / prefill / decode / dispatch "
                         "overhead) of the closed-loop window into each "
                         "row's 'hops' key; the replicated tier's "
                         "router-dispatch hop merges via forensics.py "
                         "--trace (docs/OBSERVABILITY.md)")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero unless continuous >= 1.5x batch "
                         "closed-loop tokens/sec AND lower p99 TTFT; with "
                         "--spec: spec >= 1.5x continuous at greedy "
                         "bit-parity (identical canary tokens); with "
                         "--spec-paged: composed >= 1.5x plain AND "
                         "prefix-hit TTFT <= 0.5x cold AND parity")
    args = ap.parse_args(argv)
    args.batch = args.batch or args.slots

    def merge_out(key, result):
        # these rows ride BENCH_SERVING.json NEXT TO the engine-comparison
        # row (the --spec convention) instead of overwriting it
        payload = {}
        if os.path.exists(args.out):
            try:
                with open(args.out) as f:
                    prior = json.load(f)
                payload = prior if isinstance(prior, dict) else {}
            except ValueError:
                payload = {}
        payload[key] = result
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1)

    if args.shared_prefix:
        result = run_shared_prefix(args)
        merge_out("shared_prefix", result)
        print(json.dumps(result), flush=True)
        failures = []
        if args.check:
            if not result["canary_parity"]:
                failures.append("paged canary diverged from the plain "
                                "engine")
            if result["hit_over_cold"] > 0.5:
                failures.append(
                    f"prefix-hit TTFT {result['hit_ttft_median_s']}s is "
                    f"not << cold {result['cold_ttft_median_s']}s")
            occ = result["occupancy"]
            if not (0 < occ["peak_blocks_in_use"]
                    < occ["slot_engine_equivalent_blocks"]):
                failures.append("block occupancy does not track live "
                                f"tokens: {occ}")
            if result["prefix_hit_tokens"] <= 0:
                failures.append("no prefix hits recorded")
        if failures:
            print("CHECK FAILED: " + "; ".join(failures), flush=True)
            return 1
        return 0

    if args.spec_paged:
        result = run_spec_paged(args)
        merge_out("spec_paged", result)
        print(json.dumps({k: v for k, v in result.items()
                          if k not in ("cold_ttft_s", "hit_ttft_s")}),
              flush=True)
        failures = []
        if args.check:
            if not result["canary_parity"]:
                failures.append("composed canary diverged from the plain "
                                "continuous engine")
            if result["tokens_per_sec_speedup"] < 1.5:
                failures.append(
                    f"composed speedup {result['tokens_per_sec_speedup']} "
                    "< 1.5x plain continuous")
            if result["hit_over_cold"] > 0.5:
                failures.append(
                    f"prefix-hit TTFT {result['hit_ttft_median_s']}s is "
                    f"not <= 0.5x cold {result['cold_ttft_median_s']}s")
            if result["prefix_hit_tokens"] <= 0:
                failures.append("no prefix hits recorded")
            if result["spec"]["drafted"] <= 0:
                failures.append("no draft tokens recorded")
        if failures:
            print("CHECK FAILED: " + "; ".join(failures), flush=True)
            return 1
        return 0

    if args.disagg:
        result = run_disagg(args)
        merge_out("disagg", result)
        print(json.dumps({k: v for k, v in result.items()
                          if k != "note"}), flush=True)
        failures = []
        if args.check:
            if not result["canary_parity"]:
                failures.append("disagg canary diverged (streamed-block "
                                "answers must be bit-identical to the "
                                "symmetric tier's)")
            if result["tokens_per_sec_ratio"] <= 1.0:
                failures.append(
                    f"disagg tokens/sec ratio "
                    f"{result['tokens_per_sec_ratio']} <= 1.0x symmetric")
            if result["ttft_p99_ratio"] >= 1.0:
                failures.append(
                    f"disagg p99 TTFT ratio {result['ttft_p99_ratio']} "
                    ">= 1.0x symmetric")
            kv_hop = result["disagg"]["hops"].get("kv_transfer") or {}
            if not kv_hop.get("n"):
                failures.append("no kv_transfer hop spans in the merged "
                                "disagg trace")
            if result["disagg"]["errors"] or result["symmetric"]["errors"]:
                failures.append(
                    f"request errors: disagg={result['disagg']['errors']} "
                    f"symmetric={result['symmetric']['errors']}")
            if not result["disagg"]["transfer"]["migrations"].get(
                    'outcome="ok"'):
                failures.append("no successful block migrations recorded")
        if failures:
            print("CHECK FAILED: " + "; ".join(failures), flush=True)
            return 1
        return 0

    if args.replicas >= 2:
        result = run_replicas(args)
        merge_out("replicas", result)
        print(json.dumps({k: v for k, v in result.items()
                          if k != "note"}), flush=True)
        if args.check:
            worst = min(r["scaling_efficiency"] for r in result["curve"])
            if worst < 0.7:
                print(f"CHECK FAILED: emulated replica scaling efficiency "
                      f"{worst} < 0.7", flush=True)
                return 1
        return 0

    latency = None
    if args.latency:
        latency = {int(k): float(v) for k, v in
                   (kv.split(":") for kv in args.latency.split(","))}

    spec_ctx = None
    if args.spec:
        if args.engines == "batch,continuous":
            args.engines = "continuous,spec"
        interface, draft, align = _build_spec_pair()
        print(json.dumps({"spec_alignment": align}), flush=True)
        spec_ctx = {"interface": interface, "draft": draft,
                    "orbit": _spec_perm(), "alignment": align}

    rows = []
    for engine in args.engines.split(","):
        engine = engine.strip()
        row = run_engine(engine, args, latency=latency, spec_ctx=spec_ctx)
        rows.append(row)
        print(json.dumps(row), flush=True)

    result = {
        "metric": "serving tokens/sec + TTFT/ITL @ mixed-length REST "
                  "traffic (closed+open loop)",
        "workload": list(WORKLOAD if spec_ctx is None else SPEC_WORKLOAD),
        "slots": args.slots, "batch": args.batch,
        "concurrency": args.concurrency, "rate_rps": args.rate,
        "backend": "cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
        else "default",
        "rows": rows,
    }
    if spec_ctx is not None:
        result["spec_alignment"] = spec_ctx["alignment"]
    by = {r["engine"]: r for r in rows}
    if "batch" in by and "continuous" in by:
        b = by["batch"]["closed_loop"]["tokens_per_sec"]
        c = by["continuous"]["closed_loop"]["tokens_per_sec"]
        result["tokens_per_sec_speedup"] = round(c / max(b, 1e-9), 3)
        bt, ct = by["batch"].get("ttft_p99"), by["continuous"].get("ttft_p99")
        result["ttft_p99_batch"] = bt
        result["ttft_p99_continuous"] = ct
    if "continuous" in by and "spec" in by:
        c = by["continuous"]["closed_loop"]["tokens_per_sec"]
        s = by["spec"]["closed_loop"]["tokens_per_sec"]
        result["spec_tokens_per_sec_speedup"] = round(s / max(c, 1e-9), 3)
        result["spec_canary_parity"] = (
            by["spec"]["canary"] is not None
            and by["spec"]["canary"] == by["continuous"]["canary"])
    payload = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                prior = json.load(f)
            payload = prior if isinstance(prior, dict) else {}
        except ValueError:
            payload = {}
    if args.spec:
        # the spec round rides BENCH_SERVING.json NEXT TO the PR 7
        # continuous-vs-batch row instead of overwriting it
        payload["spec"] = result
    else:
        # the headline row is the top level; re-measuring it must not
        # drop the nested spec/shared_prefix/replicas rows other modes
        # merged in earlier
        extra = {k: payload[k] for k in ("spec", "shared_prefix",
                                         "spec_paged", "replicas")
                 if k in payload}
        payload = {**result, **extra}
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=1)
    print(json.dumps({k: v for k, v in result.items() if k != "rows"}),
          flush=True)
    failures = []
    if args.check and "tokens_per_sec_speedup" in result:
        bt, ct = result["ttft_p99_batch"], result["ttft_p99_continuous"]
        # an absent quantile means the timed window recorded no TTFT
        # samples — no latency evidence either way, so the gate FAILS
        # loudly instead of passing vacuously
        if not (result["tokens_per_sec_speedup"] >= 1.5
                and bt is not None and ct is not None and ct <= bt):
            failures.append("continuous-vs-batch gate")
    if args.check and "spec_tokens_per_sec_speedup" in result:
        if result["spec_tokens_per_sec_speedup"] < 1.5:
            failures.append(
                f"spec speedup {result['spec_tokens_per_sec_speedup']} "
                "< 1.5x")
        if not result.get("spec_canary_parity"):
            failures.append("spec canary diverged from the plain engine")
    if args.check and args.spec \
            and "spec_tokens_per_sec_speedup" not in result:
        failures.append("--spec --check needs both continuous and spec rows")
    if failures:
        print("CHECK FAILED: " + "; ".join(failures), flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
