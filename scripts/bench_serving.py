#!/usr/bin/env python3
"""Serving traffic generator: batch-to-completion vs continuous batching.

Drives the REAL REST path — ``rest_api.serve`` with its isolated device
loop, HTTP child, Manager IPC, admission control — with a reproducible
mixed-length workload (short and long prompts x short and long responses,
the regime where batch-to-completion pins a whole co-batch on its longest
row), in two generator modes per engine:

* **closed loop** — C workers each firing its next request the moment the
  previous answer lands (saturation throughput), then
* **open loop** — seeded-exponential interarrivals at a target rate, each
  request on its own thread (latency under a Poisson-ish load, the number
  p99 TTFT is about).

Per engine it reports client-side tokens/sec + request outcomes and the
server-side p50/p99 TTFT + ITL scraped from ``/metrics`` (the engine
records TTFT per slot event, the batch path per stepped-loop hook — the
bench config forces ``decode_loop=stepped`` so both sides report), and
writes a BENCH_*-style row to ``BENCH_SERVING.json``.

Acceptance (ISSUE 7): on the CPU backend the continuous engine sustains
>= 1.5x the batch engine's closed-loop tokens/sec at mixed lengths with a
lower open-loop p99 TTFT; the exit code enforces it under ``--check``.

Fault schedules: ``--latency I:SEC[,I:SEC...]`` wraps the interface in
``utils.fault_injection.FaultyInterface`` (the PR 3 schedules) — decode
call I sleeps SEC first.  The schedules fire on ``complete_tokens*`` calls,
i.e. the BATCH engine's decode path (the continuous engine drives the model
directly); use them to reproduce deadline/429 behavior under a stalling
batch decode.

CPU-scale model by default (harness-size mixer, seq 64); pass a config
JSON via ``--config`` to run a real checkpoint's shape instead.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

#: harness-scale serving model: small enough that one decode iteration is
#: milliseconds on CPU, deep/wide enough that the slot pool is a real
#: multi-leaf cache pytree (depth-stacked KV + int8-composable layout)
BENCH_CONFIG = {
    "model_mode": "gpt", "use_video": False, "use_language": True,
    "sequence_length": 64, "features_per_head": 16, "heads": 2,
    "depth": 2, "train_batch_size": 1, "vocab_size": 256,
    "group_linear_factor": 2,
    "intermediate_feed_forward_multiplier_multiplier": 0.5,
    "memory_reduction_strategy": "none",
    "block_config": [
        {"layer": ["norm-shift-scale-features-group",
                   "bottleneck_group_linear-in:relu-mid:relu-mid:norm-mid:"
                   "shift-mid:scale-mid:features"]},
        {"layer": ["norm-shift-scale-features-group",
                   "attention-biased_attention_map-absolute-input_as_value-"
                   "shared"]}],
    # the stepped loop on BOTH engines: it is what reports TTFT/ITL on the
    # batch path, and fine chunks are what let the continuous engine
    # recycle finished slots quickly (chunk boundaries = scheduling points)
    "decode_loop": "stepped", "decode_chunk_tokens": 4,
    "serve_prefill_chunk_tokens": 8,
    "serve_queue_limit": 256, "serve_request_deadline_s": 120.0,
    "model_path": "/tmp/bench_serving",
}

#: mixed request classes (prompt_tokens, max_tokens): the short/long mix
#: that makes batch-to-completion pay head-of-line blocking
WORKLOAD = ((3, 4), (5, 8), (2, 16), (6, 48), (4, 4), (3, 32))


def _build_interface(config_path=None, latency=None):
    import numpy as np
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.infer.interface import InterfaceWrapper
    from homebrewnlp_tpu.model import Model
    import jax.numpy as jnp

    cfg = dict(BENCH_CONFIG)
    if config_path:
        with open(config_path) as f:
            cfg = {**json.load(f), "decode_loop": "stepped"}
    params = ModelParameter(cfg)
    params.train = False
    model = Model(params)
    seq = params.sequence_dim.size
    tps = params.token_patch_dim.size
    zeros = np.zeros((1, seq, tps), np.int32)
    variables = {k: jnp.asarray(v)
                 for k, v in model.init({"token_x": zeros,
                                         "token_y": zeros}).items()}
    interface = InterfaceWrapper(params, model, variables)
    if latency:
        from homebrewnlp_tpu.utils.fault_injection import FaultyInterface
        interface = FaultyInterface(interface, latency=latency)
    return interface


def _spawn(interface, engine: str, slots: int, batch: int):
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.infer import rest_api

    params = ModelParameter(interface.params,
                            serve_engine=engine, serve_slots=slots,
                            serve_batch_size=batch)
    params.train = False
    interface.params.serve_engine = engine   # FaultyInterface proxies params
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(target=rest_api.serve, args=(params, interface),
                         kwargs={"port": port, "isolate": True, "stop": stop},
                         daemon=True)
    t.start()
    return port, stop, t


def _post(port, payload, timeout=180.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/token_completion",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _wait_up(port, deadline_s=180.0):
    req = urllib.request.Request(f"http://127.0.0.1:{port}/health")
    t0 = time.monotonic()
    while True:
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read())
        except Exception:
            if time.monotonic() - t0 > deadline_s:
                raise
            time.sleep(0.25)


def _scrape_buckets(port):
    """Cumulative TTFT/ITL bucket counts from the /metrics exposition."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
        text = resp.read().decode()
    out = {}
    for name in ("hbnlp_serve_ttft_seconds", "hbnlp_serve_itl_seconds"):
        pat = re.compile(rf'^{name}_bucket{{le="([^"]+)"}} (\d+)', re.M)
        pairs = sorted(
            (float("inf") if le == "+Inf" else float(le), int(c))
            for le, c in pat.findall(text))
        bounds = [b for b, _ in pairs if b != float("inf")]
        cum = [c for _, c in pairs]
        out[name] = (bounds,
                     [c - (cum[i - 1] if i else 0)
                      for i, c in enumerate(cum)])
    return out


def _quantiles(before, after):
    """p50/p99 of the TIMED window: per-bucket count delta between two
    scrapes — the warmup window's compile-dominated TTFTs must not ride
    the tail of the measured distribution."""
    from homebrewnlp_tpu.telemetry.registry import histogram_quantile
    out = {}
    for name, (bounds, counts_after) in after.items():
        counts_before = before.get(name, (bounds, [0] * len(counts_after)))[1]
        counts = [a - b for a, b in zip(counts_after, counts_before)]
        key = "ttft" if "ttft" in name else "itl"
        out[f"{key}_count"] = sum(counts)
        for q in (0.5, 0.99):
            out[f"{key}_p{int(q * 100)}"] = histogram_quantile(bounds,
                                                               counts, q)
    return out


class _Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.ok = 0
        self.errors = {}
        self.generated = 0

    def record(self, status, body, prompt_len):
        with self.lock:
            if status == 200:
                self.ok += 1
                self.generated += max(0, len(body.get("tokens", ()))
                                      - prompt_len)
            else:
                key = str(status)
                self.errors[key] = self.errors.get(key, 0) + 1


def _request_for(rng, i):
    plen, mt = WORKLOAD[i % len(WORKLOAD)]
    toks = [int(x) for x in rng.integers(1, 255, plen)]
    return {"tokens": toks, "max_tokens": mt, "temperature": 0.0}, plen


def _closed_loop(port, rng, workers: int, per_worker: int):
    stats = _Stats()
    # payloads pre-drawn on this thread: numpy Generators are not
    # thread-safe, and racy draw order would break --seed reproducibility
    payloads = [[_request_for(rng, w * per_worker + i)
                 for i in range(per_worker)] for w in range(workers)]

    def worker(w):
        for payload, plen in payloads[w]:
            try:
                status, body = _post(port, payload)
            except Exception:
                stats.record(599, {}, plen)
                continue
            stats.record(status, body, plen)

    t0 = time.monotonic()
    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    return stats, wall


def _open_loop(port, rng, rate_rps: float, duration_s: float):
    stats = _Stats()
    threads = []
    t0 = time.monotonic()
    i = 0
    while time.monotonic() - t0 < duration_s:
        payload, plen = _request_for(rng, i)
        i += 1

        def fire(payload=payload, plen=plen):
            try:
                status, body = _post(port, payload)
            except Exception:
                stats.record(599, {}, plen)
                return
            stats.record(status, body, plen)

        th = threading.Thread(target=fire, daemon=True)
        th.start()
        threads.append(th)
        time.sleep(float(rng.exponential(1.0 / rate_rps)))
    for th in threads:
        th.join(timeout=180)
    wall = time.monotonic() - t0
    return stats, wall


def run_engine(engine: str, args, latency=None) -> dict:
    import numpy as np
    interface = _build_interface(args.config, latency=latency)
    port, stop, t = _spawn(interface, engine, args.slots, args.batch)
    try:
        health = _wait_up(port)
        assert (health.get("engine") or {}).get("mode") == engine, health
        # warmup: compile every program shape out of the timed window
        warm_rng = np.random.default_rng(7)
        for i in range(max(2, args.slots)):
            payload, _ = _request_for(warm_rng, i)
            _post(port, payload)
        rng = np.random.default_rng(args.seed)
        # the scrape merges the device loop's snapshot, published once per
        # loop turn — give it one idle poll to flush the warmup counts
        time.sleep(1.5)
        baseline = _scrape_buckets(port)
        closed, closed_wall = _closed_loop(port, rng, args.concurrency,
                                           args.requests)
        open_stats, open_wall = _open_loop(port, rng, args.rate,
                                           args.duration)
        time.sleep(1.5)   # final snapshot publish
        q = _quantiles(baseline, _scrape_buckets(port))
        row = {
            "engine": engine,
            "closed_loop": {
                "requests_ok": closed.ok, "errors": closed.errors,
                "generated_tokens": closed.generated,
                "wall_s": round(closed_wall, 3),
                "tokens_per_sec": round(closed.generated
                                        / max(closed_wall, 1e-9), 2),
            },
            "open_loop": {
                "rate_rps": args.rate, "requests_ok": open_stats.ok,
                "errors": open_stats.errors,
                "generated_tokens": open_stats.generated,
                "wall_s": round(open_wall, 3),
            },
            **{k: (round(v, 6) if isinstance(v, float) else v)
               for k, v in q.items()},
        }
        return row
    finally:
        stop.set()
        t.join(timeout=30)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--engines", default="batch,continuous",
                    help="comma list: batch, continuous")
    ap.add_argument("--slots", type=int, default=4,
                    help="serve_slots for the continuous engine")
    ap.add_argument("--batch", type=int, default=4,
                    help="serve_batch_size for the batch engine (kept equal "
                         "to --slots by default for a fair width match)")
    ap.add_argument("--concurrency", type=int, default=8,
                    help="closed-loop worker count")
    ap.add_argument("--requests", type=int, default=6,
                    help="closed-loop requests per worker")
    ap.add_argument("--rate", type=float, default=4.0,
                    help="open-loop arrival rate (req/s)")
    ap.add_argument("--duration", type=float, default=8.0,
                    help="open-loop duration (s)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--config", default=None,
                    help="config JSON instead of the harness-scale model")
    ap.add_argument("--latency", default=None,
                    help="FaultyInterface schedule 'I:SEC[,I:SEC...]' — "
                         "decode call I sleeps SEC (batch-path decode calls)")
    ap.add_argument("--out", default="BENCH_SERVING.json")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero unless continuous >= 1.5x batch "
                         "closed-loop tokens/sec AND lower p99 TTFT")
    args = ap.parse_args(argv)
    args.batch = args.batch or args.slots

    latency = None
    if args.latency:
        latency = {int(k): float(v) for k, v in
                   (kv.split(":") for kv in args.latency.split(","))}

    rows = []
    for engine in args.engines.split(","):
        engine = engine.strip()
        row = run_engine(engine, args, latency=latency)
        rows.append(row)
        print(json.dumps(row), flush=True)

    result = {
        "metric": "serving tokens/sec + TTFT/ITL @ mixed-length REST "
                  "traffic (closed+open loop)",
        "workload": list(WORKLOAD),
        "slots": args.slots, "batch": args.batch,
        "concurrency": args.concurrency, "rate_rps": args.rate,
        "backend": "cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
        else "default",
        "rows": rows,
    }
    by = {r["engine"]: r for r in rows}
    if "batch" in by and "continuous" in by:
        b = by["batch"]["closed_loop"]["tokens_per_sec"]
        c = by["continuous"]["closed_loop"]["tokens_per_sec"]
        result["tokens_per_sec_speedup"] = round(c / max(b, 1e-9), 3)
        bt, ct = by["batch"].get("ttft_p99"), by["continuous"].get("ttft_p99")
        result["ttft_p99_batch"] = bt
        result["ttft_p99_continuous"] = ct
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({k: v for k, v in result.items() if k != "rows"}),
          flush=True)
    if args.check and "tokens_per_sec_speedup" in result:
        bt, ct = result["ttft_p99_batch"], result["ttft_p99_continuous"]
        # an absent quantile means the timed window recorded no TTFT
        # samples — no latency evidence either way, so the gate FAILS
        # loudly instead of passing vacuously
        ok = (result["tokens_per_sec_speedup"] >= 1.5
              and bt is not None and ct is not None and ct <= bt)
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
