#!/usr/bin/env python3
"""graft-lint: run the repo's static-analysis layer from one entry point.

Two halves (docs/STATIC_ANALYSIS.md):

  --ast   AST rules over ``homebrewnlp_tpu/`` and ``scripts/`` (wall-clock
          discipline, unseeded rngs, donated-jit registration, config-docs
          coverage).  Stdlib-only, runs in well under a second.
  --hlo   compiled-HLO audit of every registered jitted entry point (train
          step, decode chunk step, prefill entry, eval fn): donation,
          big-copy, dtype-promotion, collective census vs
          ``analysis/budgets.json``, host-sync.  Compiles a small audit
          model on the current backend (~15 s on one CPU).
  --all   both (the pre-push / CI mode; also the default with no flags).

Exit status is the number of findings clamped to 1 — nonzero means the
repo violates an invariant.  The summary groups findings per rule so CI
logs show at a glance WHICH invariant broke.
"""
from __future__ import annotations

import argparse
import collections
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run_ast() -> list:
    from homebrewnlp_tpu.analysis import ast_lint
    return ast_lint.lint_repo()


def run_hlo(budgets_path=None, ledger_path=None) -> list:
    from homebrewnlp_tpu.analysis import cost_ledger, entry_points, hlo_lint
    budgets = hlo_lint.load_budgets(budgets_path) if budgets_path else None
    # one lower_all feeds BOTH the HLO audits and the cost-ledger
    # regression check — the four entry-point compiles are the cost here,
    # shared so --all stays within its ~20s CPU budget
    lowered = entry_points.lower_all()
    findings = entry_points.audit_lowered(lowered, budgets=budgets)
    findings += cost_ledger.ledger_audit(lowered, path=ledger_path)
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ast", action="store_true",
                    help="AST rules only (fast, no jax)")
    ap.add_argument("--hlo", action="store_true",
                    help="compiled-HLO entry-point audit only")
    ap.add_argument("--all", action="store_true",
                    help="both halves (default when no flags given)")
    ap.add_argument("--budgets", default=None,
                    help="alternate budgets.json (default: "
                         "analysis/budgets.json)")
    ap.add_argument("--ledger", default=None,
                    help="alternate cost_ledger.json (default: "
                         "analysis/cost_ledger.json)")
    args = ap.parse_args(argv)
    do_ast = args.ast or args.all or not (args.ast or args.hlo)
    do_hlo = args.hlo or args.all or not (args.ast or args.hlo)

    findings = []
    t0 = time.monotonic()
    if do_ast:
        findings += run_ast()
    if do_hlo:
        findings += run_hlo(args.budgets, args.ledger)
    dt = time.monotonic() - t0

    for f in findings:
        print(f)
    per_rule = collections.Counter(f.rule for f in findings)
    halves = "+".join(h for h, on in (("ast", do_ast), ("hlo", do_hlo)) if on)
    if findings:
        summary = ", ".join(f"{rule}: {n}" for rule, n
                            in sorted(per_rule.items()))
        print(f"graft-lint [{halves}]: {len(findings)} finding(s) in "
              f"{dt:.1f}s — {summary}", file=sys.stderr)
        return 1
    print(f"graft-lint [{halves}]: clean in {dt:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
