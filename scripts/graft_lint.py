#!/usr/bin/env python3
"""graft-lint: run the repo's static-analysis layer from one entry point.

Three pass families (docs/STATIC_ANALYSIS.md):

  --ast   AST rules over ``homebrewnlp_tpu/`` and ``scripts/`` (wall-clock
          discipline, unseeded rngs, donated-jit registration, mesh-axis
          literals, config-docs coverage).  Stdlib-only, runs in well
          under a second.
  --hlo   compiled-HLO audit of every registered jitted entry point (train
          step, decode chunk step, prefill entry, eval fn, engine chunk
          step): donation, big-copy, dtype-promotion, collective census vs
          ``analysis/budgets.json``, host-sync, cost-ledger regression.
          Compiles a small audit model on the current backend (~15 s CPU).
  --conc  host-concurrency audit (analysis/conc_lint.py): lock-discipline
          AST lint over the serving/elastic control plane (GUARDED_BY
          registry, blocking-call-under-lock, lock-ordering cycles,
          thread hygiene) plus the deterministic interleaving explorer
          (analysis/interleave.py) replaying the control-plane scenarios
          under permuted schedules.  With ``HBNLP_LOCK_TRACE=<dir>``
          pointing at a recorded run, the observed acquisition-order
          edges join the same cycle check.
  --mesh  mesh-aware audit (analysis/mesh_audit.py): the registered entry
          points lowered under every pod_lowering strategy (dp x tp, ring
          SP, MoE EP, the pipeline schedules) on 8 virtual CPU devices —
          per-mesh collective budgets (surplus collectives named WITH the
          mesh axis they reshard over), sharding-spec contracts, peak-HBM
          liveness.  When the current process has fewer than 8 devices the
          mesh half re-runs itself in a CPU-virtual subprocess (the dryrun
          bootstrap idiom), so the single-device --hlo audit keeps the
          current backend.
  --all   everything (the pre-push / CI mode; also the default with no
          flags).  The single-device entry points are lowered ONCE and
          shared between the HLO audits and the cost-ledger check; the
          mesh half lowers only its sharded variants.

Exit status is the number of findings clamped to 1 — nonzero means the
repo violates an invariant.  The summary groups findings per rule so CI
logs show at a glance WHICH invariant broke.
"""
from __future__ import annotations

import argparse
import collections
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run_ast() -> list:
    from homebrewnlp_tpu.analysis import ast_lint
    return ast_lint.lint_repo()


def run_conc() -> list:
    # the blockpool scenario imports infer/paged -> engine -> jax; pin the
    # platform so --conc never grabs a TPU from a CI box that has one
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from homebrewnlp_tpu.analysis import conc_lint
    edges = set()
    findings = conc_lint.explorer_findings(edges=edges)
    findings += conc_lint.lint_repo_conc(extra_edges=edges)
    return findings


def run_hlo(budgets_path=None, ledger_path=None) -> list:
    from homebrewnlp_tpu.analysis import cost_ledger, entry_points, hlo_lint
    budgets = hlo_lint.load_budgets(budgets_path) if budgets_path else None
    # one lower_all feeds BOTH the HLO audits and the cost-ledger
    # regression check — the five entry-point compiles are the cost here,
    # shared so --all stays within its CPU time budget
    lowered = entry_points.lower_all()
    findings = entry_points.audit_lowered(lowered, budgets=budgets)
    findings += cost_ledger.ledger_audit(lowered, path=ledger_path)
    return findings


_FINDING_LINE = re.compile(r"^\[([\w-]+)\] ([^:]+): (.*)$")


def run_mesh(budgets_path=None) -> list:
    """Mesh passes in-process when the process already exposes 8 CPU
    devices (the test rig), else in a CPU-virtual subprocess so the
    --hlo half keeps auditing the CURRENT backend.  The committed meshes
    budgets are CPU-virtual lowerings by definition — auditing them
    against a TPU backend's compile would flag honest backend drift as
    findings, so a non-CPU process always takes the subprocess."""
    import jax

    from homebrewnlp_tpu.analysis import hlo_lint, mesh_audit

    if budgets_path:
        budgets_path = os.path.abspath(budgets_path)
    if (jax.default_backend() == "cpu"
            and len(jax.devices()) >= mesh_audit.MESH_DEVICES):
        budgets = (hlo_lint.load_budgets(budgets_path)
                   if budgets_path else None)
        findings, skipped = mesh_audit.audit_meshes(budgets)
        for name, reason in sorted(skipped.items()):
            print(f"mesh-audit: strategy {name!r} SKIPPED — environment "
                  f"gap: {reason}")
        return findings

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    flags += (" --xla_force_host_platform_device_count="
              f"{mesh_audit.MESH_DEVICES}")
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               XLA_FLAGS=flags)
    cmd = [sys.executable, "-m", "homebrewnlp_tpu.analysis.mesh_audit",
           "--check"]
    if budgets_path:
        cmd += ["--budgets", budgets_path]
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True)
    findings = []
    for line in proc.stdout.splitlines():
        m = _FINDING_LINE.match(line)
        if m is not None:
            findings.append(hlo_lint.Finding(m.group(1), m.group(2),
                                             m.group(3)))
        elif line.startswith("mesh-audit: strategy"):
            print(line)
    if proc.returncode != 0 and not findings:
        findings.append(hlo_lint.Finding(
            "mesh-audit", "subprocess",
            f"mesh audit subprocess failed (rc={proc.returncode}):\n"
            + (proc.stderr or proc.stdout)[-2000:]))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ast", action="store_true",
                    help="AST rules only (fast, no jax)")
    ap.add_argument("--conc", action="store_true",
                    help="host-concurrency audit only (lock lint + "
                         "interleaving explorer)")
    ap.add_argument("--hlo", action="store_true",
                    help="compiled-HLO entry-point audit only")
    ap.add_argument("--mesh", action="store_true",
                    help="mesh-aware strategy audit only (8 virtual CPU "
                         "devices)")
    ap.add_argument("--all", action="store_true",
                    help="every pass family (default when no flags given)")
    ap.add_argument("--budgets", default=None,
                    help="alternate budgets.json (default: "
                         "analysis/budgets.json)")
    ap.add_argument("--ledger", default=None,
                    help="alternate cost_ledger.json (default: "
                         "analysis/cost_ledger.json)")
    args = ap.parse_args(argv)
    none_picked = not (args.ast or args.conc or args.hlo or args.mesh)
    do_ast = args.ast or args.all or none_picked
    do_conc = args.conc or args.all or none_picked
    do_hlo = args.hlo or args.all or none_picked
    do_mesh = args.mesh or args.all or none_picked

    findings = []
    t0 = time.monotonic()
    if do_ast:
        findings += run_ast()
    if do_conc:
        findings += run_conc()
    if do_hlo:
        findings += run_hlo(args.budgets, args.ledger)
    if do_mesh:
        findings += run_mesh(args.budgets)
    dt = time.monotonic() - t0

    for f in findings:
        print(f)
    per_rule = collections.Counter(f.rule for f in findings)
    halves = "+".join(h for h, on in (("ast", do_ast), ("conc", do_conc),
                                      ("hlo", do_hlo),
                                      ("mesh", do_mesh)) if on)
    if findings:
        summary = ", ".join(f"{rule}: {n}" for rule, n
                            in sorted(per_rule.items()))
        print(f"graft-lint [{halves}]: {len(findings)} finding(s) in "
              f"{dt:.1f}s — {summary}", file=sys.stderr)
        return 1
    print(f"graft-lint [{halves}]: clean in {dt:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
