#!/usr/bin/env python3
"""Fleet manager: launch training, watch health, recover from preemption.

Equivalent of /root/reference/scripts/run_manager.py:94-146 — creates the TPU,
launches the training subprocess, polls health every 5-10 minutes, and on an
unhealthy (preempted) TPU kills the process group, recreates the TPU, and
relaunches the run (training resumes from its checkpoint + deterministic data
log).  Two health sources:

- TPU health via pluggable shell commands (``--create-cmd``/``--health-cmd``/
  ``--delete-cmd``, e.g. ``gcloud compute tpus tpu-vm ...``; the reference
  hard-coded its TPUServiceAPI).  Empty commands skip TPU management — useful
  when the manager only supervises the process (this container).
- training liveness via the run's ``metrics.jsonl`` heartbeat: if no step is
  logged for ``--stall-timeout`` seconds the run counts as stalled and is
  restarted (the reference had no stall detection).
"""
import argparse
import os
import random
import signal
import subprocess
import sys
import tempfile
import threading
import time
import typing

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


#: training exits with this code after a SIGTERM-triggered emergency
#: checkpoint (homebrewnlp_tpu/run/train_loop.py PREEMPTED_EXIT_CODE — kept
#: as a literal here so the manager never imports jax): a clean preemption,
#: to be relaunched, not a finished or crashed run
PREEMPTED_RC = 143


def sh(cmd: str) -> subprocess.CompletedProcess:
    return subprocess.run(cmd, shell=True, capture_output=True, text=True,
                          timeout=1800)


class Manager:
    def __init__(self, args):
        self.args = args
        # manager log lives with the run artifacts — through the fs seam so
        # remote model_paths (gs://...) work like the reference's GFile log
        # adapter (reference run_manager.py:26-56).  The training
        # subprocess's stdout needs a real fd, so remote paths tee it to a
        # local spool file instead.
        from homebrewnlp_tpu.utils import fs
        if not args.model_path:
            self.log = sys.stderr
        elif fs.is_local(args.model_path):
            os.makedirs(args.model_path, exist_ok=True)
            self.log = open(os.path.join(args.model_path, "run.log"), "a")
        else:
            fs.makedirs(args.model_path)
            self.log = fs.open_(fs.join(args.model_path, "run.log"), "a")

    def out(self, msg: str):
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        self.log.write(f"[{stamp}] {msg}\n")
        self.log.flush()

    def tpu_healthy(self) -> bool:
        if not self.args.health_cmd:
            return True
        r = sh(self.args.health_cmd)
        return r.returncode == 0 and ("READY" in r.stdout or "healthy" in
                                      r.stdout.lower() or not r.stdout.strip())

    def create_tpu(self, recreate: bool = False):
        if recreate and self.args.delete_cmd:
            self.out(f"deleting TPU: {self.args.delete_cmd}")
            sh(self.args.delete_cmd)
            time.sleep(30)
        if self.args.create_cmd:
            self.out(f"creating TPU: {self.args.create_cmd}")
            for attempt in range(20):
                r = sh(self.args.create_cmd)
                if r.returncode == 0:
                    break
                self.out(f"create failed (attempt {attempt}): {r.stderr[-500:]}")
                time.sleep(60)
        # readiness wait with recreate-on-slow (reference :94-109)
        waited = 0
        while not self.tpu_healthy():
            time.sleep(15)
            waited += 15
            if waited > 15 * 15 and self.args.create_cmd:
                self.out("TPU slow to become ready; recreating")
                self.create_tpu(recreate=True)
                return

    def heartbeat_age(self) -> float:
        path = os.path.join(self.args.model_path, "metrics.jsonl") \
            if self.args.model_path else None
        if not path or not os.path.exists(path):
            return 0.0
        # compared against a file mtime, which is epoch wall time — a
        # monotonic clock cannot age it  # graft-lint: allow[wallclock]
        return time.time() - os.path.getmtime(path)

    _spool_path = None
    _spool = None

    def launch(self) -> subprocess.Popen:
        self.out(f"launching: {self.args.run_command}")
        if hasattr(self.log, "fileno"):
            sink = self.log
        else:
            # remote run.log has no fd for subprocess redirection: spool
            # locally, then upload_spool() appends it remotely on every poll
            # tick / restart so crash tracebacks survive VM preemption
            self.upload_spool()
            if self._spool is not None:
                self._spool.close()
            self._spool_path = os.path.join(
                tempfile.gettempdir(), f"run_manager_spool_{os.getpid()}.log")
            self._spool = sink = open(self._spool_path, "w")
        return subprocess.Popen(self.args.run_command, shell=True,
                                stdout=sink, stderr=sink,
                                preexec_fn=os.setsid)

    def upload_spool(self):
        """Append spooled subprocess output to the remote run.log."""
        if self._spool_path is None or not os.path.exists(self._spool_path):
            return
        with open(self._spool_path) as f:
            data = f.read()
        if data:
            self.log.write(data)
            self.log.flush()
        open(self._spool_path, "w").close()  # consumed

    def kill(self, proc: subprocess.Popen,
             grace: typing.Optional[int] = None):
        # SIGTERM now triggers a GRACEFUL stop in training (finish the step,
        # write the emergency checkpoint — potentially minutes for GB-scale
        # state on gs://); a fixed short TERM->KILL gap would tear exactly
        # the checkpoint the preemption path exists to write.  Callers pass
        # a SHORT grace for a wedged (stalled) process that will never
        # honour the graceful flag.
        if grace is None:
            grace = getattr(self.args, "term_grace", 600)
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except ProcessLookupError:
            return
        try:
            proc.wait(timeout=grace)
            return
        except subprocess.TimeoutExpired:
            self.out(f"no exit {grace}s after SIGTERM; escalating to SIGKILL")
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except ProcessLookupError:
            pass

    def run(self):
        self.create_tpu()
        proc = self.launch()
        restarts = 0
        while True:
            time.sleep(self.args.poll_interval
                       + random.randint(0, self.args.poll_jitter))
            self.upload_spool()
            healthy = self.tpu_healthy()
            stalled = (self.args.stall_timeout > 0
                       and self.heartbeat_age() > self.args.stall_timeout)
            rc = proc.poll()  # snapshot once: the process may exit mid-tick
            preempted = rc == PREEMPTED_RC
            if rc is not None and not preempted:
                if healthy:
                    self.out(f"training exited rc={rc}; done")
                    break
                # process died because the TPU went away — fall through
            if preempted:
                # clean, resumable exit: relaunch WITHOUT consuming the
                # crash budget (max_restarts bounds crash loops, and a
                # preemption is not a crash)
                self.out(f"training exited rc={PREEMPTED_RC}: clean "
                         "preemption (emergency checkpoint written); "
                         "relaunching")
            elif healthy and not stalled:
                continue
            else:
                restarts += 1
                if 0 < self.args.max_restarts < restarts:
                    self.out("max restarts exceeded; giving up")
                    break
                self.out(f"unhealthy={not healthy} stalled={stalled}; "
                         f"restarting (#{restarts})")
            # a stalled (wedged) process never honours the graceful flag:
            # don't park the fleet manager on the full checkpoint grace
            self.kill(proc, grace=15 if stalled else None)
            time.sleep(60)
            self.create_tpu(recreate=not healthy)
            proc = self.launch()
        self.upload_spool()
        if self.args.delete_cmd:
            self.out("deleting TPU")
            sh(self.args.delete_cmd)


def _free_port() -> int:
    from homebrewnlp_tpu.distributed.bootstrap import free_port
    return free_port()


class Fleet(Manager):
    """Slice-aware local fan-out (docs/DISTRIBUTED.md): N coordinator-wired
    processes on THIS host — the CPU multiprocess rig, and the shape a
    per-host pod launcher drives one host at a time.

    Each worker gets the explicit-flag bootstrap env
    (``HBNLP_COORDINATOR``/``HBNLP_NUM_PROCESSES``/``HBNLP_PROCESS_ID``,
    homebrewnlp_tpu/distributed/bootstrap.py) plus — on the CPU rig — a
    forced CPU backend with ``--devices-per-process`` virtual devices.
    Output is multiplexed into the manager log with a ``[pN]`` prefix per
    line.

    Restart semantics mirror the single-process manager, fleet-wide:

    - ANY worker exiting 143 = pod-wide preemption (the chief-flag
      broadcast inside the train loop makes every worker stop and write
      the SAME emergency checkpoint) → wait for the rest, relaunch ALL
      without consuming the crash budget.
    - any worker crashing (nonzero, non-143) → its peers are already doomed
      (their next collective would hang on the dead rank) → TERM the rest,
      relaunch ALL, consuming one restart.
    - all zero → done.
    """

    def __init__(self, args):
        super().__init__(args)
        self._pump_threads: typing.List[threading.Thread] = []
        #: the CURRENT generation's world size — fixed here; the elastic
        #: subclass re-derives it per generation
        self.num_processes = args.num_processes

    def fleet_env(self) -> typing.Dict[str, str]:
        """Extra env for every worker of the next generation (the elastic
        subclass stamps HBNLP_GENERATION here)."""
        return {}

    def _pump(self, pid: int, stream):
        """Per-process log prefixing: every worker line lands in the
        manager log as ``[pN] line`` (reader thread per worker — pipes
        would deadlock on a filled buffer otherwise)."""
        for line in iter(stream.readline, ""):
            self.out(f"[p{pid}] {line.rstrip()}")
        stream.close()

    def join_pumps(self, timeout: float = 10.0) -> None:
        """Drain the reader threads through teardown: a dying rank's LAST
        lines — written during the TERM→KILL grace window, exactly the
        forensically interesting ones (membership markers, emergency-save
        progress, tracebacks) — land in the manager log before the next
        generation launches or the manager exits.  Called after every
        fleet teardown; the threads see EOF once their process is dead, so
        the joins are bounded."""
        for t in self._pump_threads:
            t.join(timeout=timeout)
        self._pump_threads = []

    def launch_fleet(self) -> typing.List[subprocess.Popen]:
        n = self.num_processes
        port = _free_port()  # fresh per generation: no TIME_WAIT rebind race
        self.out(f"launching fleet: {n} processes, coordinator "
                 f"localhost:{port}: {self.args.run_command}")
        procs = []
        for pid in range(n):
            env = dict(os.environ,
                       HBNLP_COORDINATOR=f"localhost:{port}",
                       HBNLP_NUM_PROCESSES=str(n),
                       HBNLP_PROCESS_ID=str(pid),
                       **self.fleet_env())
            if self.args.cpu_rig:
                import re
                flags = re.sub(
                    r"--xla_force_host_platform_device_count=\d+", "",
                    env.get("XLA_FLAGS", ""))
                env["JAX_PLATFORMS"] = "cpu"
                env["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count="
                    f"{self.args.devices_per_process}")
            p = subprocess.Popen(self.args.run_command, shell=True, env=env,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True,
                                 preexec_fn=os.setsid)
            t = threading.Thread(target=self._pump, args=(pid, p.stdout),
                                 daemon=True, name=f"fleet-pump-{pid}")
            t.start()
            self._pump_threads.append(t)
            procs.append(p)
        return procs

    def kill_fleet(self, procs, grace: typing.Optional[int] = None):
        for p in procs:
            if p.poll() is None:
                self.kill(p, grace=grace)
        # every worker is down: drain its remaining output before the
        # caller relaunches or returns (the last lines of a dying rank
        # must not race the reader thread's demise)
        self.join_pumps()

    def terminate_fleet(self, procs, grace: typing.Optional[int] = None):
        """Graceful pod-wide stop: SIGTERM EVERY worker first (the shape a
        real preemption has — all hosts signalled within the same step
        window, so the pod-wide stop agreement and the step-tagged
        emergency-save barriers line up), then wait out the shared
        checkpoint grace, then put stragglers down.  ``kill_fleet`` by
        contrast TERMs one process at a time with a full wait between —
        fine for tearing down a crashed generation, wrong for a rotation
        whose survivors must checkpoint TOGETHER."""
        if grace is None:
            grace = getattr(self.args, "term_grace", 600)
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.monotonic() + grace
        while any(p.poll() is None for p in procs) \
                and time.monotonic() < deadline:
            time.sleep(1)
        self.kill_fleet(procs, grace=15)

    def run(self):
        try:
            self._run_fleet_loop()
        finally:
            # clean finishes and give-ups alike: drain the readers so the
            # final worker lines are in the log before the manager exits
            self.join_pumps()

    def _run_fleet_loop(self):
        procs = self.launch_fleet()
        restarts = 0
        while True:
            time.sleep(self.args.poll_interval
                       + random.randint(0, self.args.poll_jitter))
            rcs = [p.poll() for p in procs]
            stalled = (self.args.stall_timeout > 0
                       and self.heartbeat_age() > self.args.stall_timeout)
            if all(rc is None for rc in rcs) and not stalled:
                continue
            preempted = any(rc == PREEMPTED_RC for rc in rcs)
            crashed = any(rc not in (None, 0, PREEMPTED_RC) for rc in rcs)
            if not preempted and not crashed and not stalled \
                    and any(rc is None for rc in rcs):
                # staggered CLEAN finish: some workers exited 0 while the
                # chief is still flushing final artifacts (telemetry dump,
                # async-checkpoint close on slow storage) — keep waiting;
                # a worker that never finishes is the stall detector's job
                continue
            if preempted:
                # clean pod-wide preemption: peers agreed via the chief-flag
                # broadcast — give stragglers the full checkpoint grace
                # before escalating, then relaunch WITHOUT consuming budget
                self.out(f"fleet preempted (rcs={rcs}): waiting for peers, "
                         "then relaunching")
                deadline = time.monotonic() + getattr(
                    self.args, "term_grace", 600)
                while any(p.poll() is None for p in procs) \
                        and time.monotonic() < deadline:
                    time.sleep(1)
                self.kill_fleet(procs, grace=15)
            elif all(rc == 0 for rc in rcs):
                self.out("fleet finished cleanly; done")
                break
            else:
                # crash or stall: a dead rank hangs every peer's next
                # collective — tear the whole generation down and relaunch
                restarts += 1
                if 0 < self.args.max_restarts < restarts:
                    self.out(f"fleet rcs={rcs} stalled={stalled}; max "
                             "restarts exceeded; giving up")
                    self.kill_fleet(procs, grace=15)
                    return
                self.out(f"fleet unhealthy (rcs={rcs} stalled={stalled}); "
                         f"restarting (#{restarts})")
                self.kill_fleet(procs, grace=15 if stalled else None)
            time.sleep(self.args.restart_delay)
            procs = self.launch_fleet()


class ElasticFleet(Fleet):
    """Elastic controller (``--elastic``, docs/DISTRIBUTED.md 'Elasticity').

    ``--num-processes`` becomes the TARGET capacity, not a fixed world
    size: every generation is launched at whatever world size the fleet
    can actually field, stamped with ``HBNLP_GENERATION`` and a fresh
    coordinator port, and resumes from the freshest COMPLETE checkpoint
    (training's restore walk).  Membership transitions, none needing a
    human:

    - **lease lapse / SIGKILL / collateral abort** — survivors self-exit
      144 (their lease agents detected the lapse; jax's own runtime may
      SIGABRT some first, same signal to us): tear the generation down,
      relaunch at ``world - dead`` WITHOUT consuming the crash budget —
      capacity loss is not a crash.  The dead count comes from the agents'
      membership marker when one was written, else from the exit census.
    - **preemption notice** (``<model_path>/elastic/preempt.json``,
      ``{"processes": [ranks]}`` or ``{"count": n}``) — PROACTIVE graceful
      shrink through the 143 path: SIGTERM the fleet (emergency checkpoint,
      no lost steps), relaunch at the reduced size, clear the notice.
    - **grow** — once shrunken, when capacity is back (``--capacity-cmd``
      exits 0; empty = always, the local-rig default), at least
      ``--grow-delay`` s have passed, and the shrunken generation has
      COMMITTED a checkpoint of its own (proof it resumed healthily, and
      the re-admission boundary the new member joins at): rotate
      gracefully through the same 143 path back to the target size.
    - plain crash (no membership signal) / stall / pod-wide 143 / clean
      finish keep the rigid fleet's semantics at the current world size.
    """

    def __init__(self, args):
        super().__init__(args)
        if not args.model_path:
            raise SystemExit("--elastic needs --model-path (membership "
                             "markers and checkpoints live there)")
        # jax-free controller helpers: distributed/elastic.py top level
        # imports nothing jax-adjacent (the worker-side agent does, lazily)
        from homebrewnlp_tpu.distributed import elastic as elastic_mod
        self.elastic = elastic_mod
        self.target = args.num_processes
        self.gen = 0
        #: checkpoint step observed when the last shrink happened; a LATER
        #: committed step is the grow boundary.  None = never shrunk
        self._shrink_ckpt: typing.Optional[int] = None
        self._gen_started = time.monotonic()

    def fleet_env(self) -> typing.Dict[str, str]:
        return {"HBNLP_GENERATION": str(self.gen)}

    def _next_generation(self, world: int) -> typing.List[subprocess.Popen]:
        self.gen += 1
        self.num_processes = world
        self._gen_started = time.monotonic()
        time.sleep(self.args.restart_delay)
        return self.launch_fleet()

    def _drain(self, procs: typing.List[subprocess.Popen], grace: int):
        """Give survivors their self-exit window (lease timeout + agent
        grace), then put the stragglers down — a rank wedged in a dead
        collective never exits on its own."""
        deadline = time.monotonic() + grace
        while any(p.poll() is None for p in procs) \
                and time.monotonic() < deadline:
            time.sleep(1)
        self.kill_fleet(procs, grace=15)

    def _latest_step(self) -> int:
        return self.elastic.latest_complete_step(self.args.model_path)

    def _capacity_ok(self) -> bool:
        if not self.args.capacity_cmd:
            return True  # local rig: a killed process is always replaceable
        return sh(self.args.capacity_cmd).returncode == 0

    def _grow_ready(self) -> bool:
        return (self.num_processes < self.target
                and time.monotonic() - self._gen_started
                >= self.args.grow_delay
                and self._latest_step() > (self._shrink_ckpt
                                           if self._shrink_ckpt is not None
                                           else -1)
                and self._capacity_ok())

    def run(self):
        try:
            self._run_elastic_loop()
        finally:
            self.join_pumps()

    def _run_elastic_loop(self):
        self.out(f"elastic controller: target {self.target} processes, "
                 f"model_path {self.args.model_path}")
        procs = self.launch_fleet()
        restarts = 0
        while True:
            time.sleep(self.args.poll_interval
                       + random.randint(0, self.args.poll_jitter))
            rcs = [p.poll() for p in procs]
            classes = [self.elastic.classify_exit(rc) for rc in rcs]
            stalled = (self.args.stall_timeout > 0
                       and self.heartbeat_age() > self.args.stall_timeout)
            notice = self.elastic.read_preempt_notice(self.args.model_path)
            if all(rc is None for rc in rcs) and not stalled:
                if notice:
                    leaving = len(notice.get("processes", [])) \
                        or int(notice.get("count", 0)) or 1
                    world = self.num_processes - leaving
                    if world < 1:
                        self.out(f"elastic: preemption notice {notice} "
                                 "leaves no capacity; graceful full stop")
                        self.kill_fleet(procs)
                        self.elastic.clear_preempt_notice(
                            self.args.model_path)
                        return
                    self.out(f"elastic: preemption notice {notice}; "
                             f"graceful shrink {self.num_processes} -> "
                             f"{world} (emergency checkpoint via SIGTERM)")
                    self.terminate_fleet(procs)  # 143: checkpoint + exit
                    self.elastic.clear_preempt_notice(self.args.model_path)
                    self._shrink_ckpt = self._latest_step()
                    procs = self._next_generation(world)
                elif self._grow_ready():
                    step = self._latest_step()
                    self.out(f"elastic: capacity back and checkpoint "
                             f"boundary reached (step {step} > shrink-time "
                             f"{self._shrink_ckpt}); graceful grow "
                             f"{self.num_processes} -> {self.target}")
                    self.terminate_fleet(procs)  # 143: checkpoint + exit
                    self._shrink_ckpt = None
                    procs = self._next_generation(self.target)
                continue
            # a membership change needs EVIDENCE of capacity loss: a rank
            # SIGKILLed from outside, a survivor's 144 self-exit, or the
            # agents' marker on shared storage.  Collateral exits alone
            # (every rank SIGABRT/SEGV, no kill, no marker) are a fleet
            # CRASH — the known single-core heartbeat-starvation flake has
            # exactly that shape, and shrinking a healthy pod on it would
            # bleed capacity with nothing actually dead
            membership = (any(c in ("membership", "killed")
                              for c in classes)
                          or (any(c == "collateral" for c in classes)
                              and self.elastic.read_membership_marker(
                                  self.args.model_path, self.gen)
                              is not None))
            if membership:
                # survivors are self-exiting 144; the lease window + agent
                # grace bounds how long that takes
                self._drain(procs, grace=self.args.elastic_drain)
                rcs = [p.poll() for p in procs]
                classes = [self.elastic.classify_exit(rc) for rc in rcs]
                marker = self.elastic.read_membership_marker(
                    self.args.model_path, self.gen)
                if marker:
                    # a lapsed lease names WHO the pod lost contact with,
                    # not WHY: a survivor the gloo runtime SIGABRTed on the
                    # dead rank's closed sockets ("another task died")
                    # loses its lease too, but its host is fine — cross the
                    # marker with the exit census and count only ranks that
                    # were killed from outside as lost capacity.  If none
                    # classify as killed (a wedged-forever rank drain had
                    # to TERM), trust the lease verdict as-is.
                    lapsed = {pid for pid in set(marker.get("lapsed", []))
                              if 0 <= pid < len(classes)}
                    dead = sum(1 for pid in lapsed
                               if classes[pid] == "killed") \
                        or len(lapsed)
                else:
                    # exit-code census fallback: only an outside SIGKILL is
                    # lost CAPACITY — a survivor that crashed on the dead
                    # rank's closed sockets before its lease agent fired is
                    # collateral, not a second lost host
                    dead = sum(1 for c in classes if c == "killed")
                dead = max(1, dead)
                world = self.num_processes - dead
                self.out(f"elastic: membership change generation "
                         f"{self.gen} (rcs={rcs}, marker={marker}): "
                         f"{dead} rank(s) lost")
                if world < 1:
                    self.out("elastic: no survivors; giving up")
                    return
                # a notice whose capacity loss already materialized as this
                # membership change must not shrink the pod a SECOND time
                # after the relaunch; tooling re-announces if more is coming
                self.elastic.clear_preempt_notice(self.args.model_path)
                self._shrink_ckpt = self._latest_step()
                self.out(f"elastic: resuming {world} survivor(s) from "
                         f"checkpoint step {self._shrink_ckpt} "
                         f"(generation {self.gen + 1}); no crash budget "
                         "consumed")
                procs = self._next_generation(world)
                continue
            if all(rc == 0 for rc in rcs):
                self.out("fleet finished cleanly; done")
                return
            if any(rc == PREEMPTED_RC for rc in rcs) and not stalled:
                if not all(rc is not None for rc in rcs):
                    continue  # stragglers still writing their checkpoint
                self.out(f"fleet preempted (rcs={rcs}); relaunching at "
                         f"world size {self.num_processes}")
                procs = self._next_generation(self.num_processes)
                continue
            if any(rc is None for rc in rcs) and not stalled:
                continue  # staggered clean finish (see Fleet.run)
            restarts += 1
            if 0 < self.args.max_restarts < restarts:
                self.out(f"fleet rcs={rcs} stalled={stalled}; max restarts "
                         "exceeded; giving up")
                self.kill_fleet(procs, grace=15)
                return
            self.out(f"fleet unhealthy (rcs={rcs} stalled={stalled}); "
                     f"restarting (#{restarts}) at world size "
                     f"{self.num_processes}")
            self.kill_fleet(procs, grace=15 if stalled else None)
            procs = self._next_generation(self.num_processes)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("run_command", help="training command to supervise")
    ap.add_argument("--model-path", default="", help="run dir (logs, heartbeat)")
    ap.add_argument("--create-cmd", default="", help="shell cmd creating the TPU")
    ap.add_argument("--health-cmd", default="", help="shell cmd checking TPU health")
    ap.add_argument("--delete-cmd", default="", help="shell cmd deleting the TPU")
    ap.add_argument("--poll-interval", type=int, default=300)
    ap.add_argument("--poll-jitter", type=int, default=300)
    ap.add_argument("--stall-timeout", type=int, default=3600)
    ap.add_argument("--term-grace", type=int, default=600, dest="term_grace",
                    help="seconds to wait after SIGTERM for the training "
                         "process to finish its emergency checkpoint "
                         "before SIGKILL")
    ap.add_argument("--max-restarts", type=int, default=0, help="0 = unlimited")
    ap.add_argument("--num-processes", type=int, default=0,
                    dest="num_processes",
                    help="fan out N coordinator-wired local processes "
                         "(docs/DISTRIBUTED.md); 0 = supervise run_command "
                         "as a single process (the per-host pod shape)")
    ap.add_argument("--devices-per-process", type=int, default=1,
                    dest="devices_per_process",
                    help="virtual CPU devices per fanned-out process "
                         "(--cpu-rig only)")
    ap.add_argument("--cpu-rig", action="store_true", default=True,
                    dest="cpu_rig",
                    help="force JAX_PLATFORMS=cpu + virtual devices in the "
                         "fleet (default; --no-cpu-rig passes the "
                         "environment through for accelerator hosts)")
    ap.add_argument("--no-cpu-rig", action="store_false", dest="cpu_rig")
    ap.add_argument("--restart-delay", type=int, default=5,
                    dest="restart_delay",
                    help="seconds between fleet teardown and relaunch")
    ap.add_argument("--elastic", action="store_true",
                    help="elastic membership (docs/DISTRIBUTED.md "
                         "'Elasticity'): --num-processes becomes the "
                         "TARGET capacity; the controller shrinks to the "
                         "survivors on a lease lapse / preemption notice "
                         "and grows back at a checkpoint boundary — no "
                         "human, no fixed world size.  Workers need "
                         "elastic_training: true")
    ap.add_argument("--grow-delay", type=int, default=60, dest="grow_delay",
                    help="(--elastic) minimum seconds a shrunken "
                         "generation runs before growing back")
    ap.add_argument("--capacity-cmd", default="", dest="capacity_cmd",
                    help="(--elastic) shell cmd probing whether target "
                         "capacity is available (rc 0 = yes); empty = "
                         "always (the local rig)")
    ap.add_argument("--elastic-drain", type=int, default=60,
                    dest="elastic_drain",
                    help="(--elastic) seconds to let survivors self-exit "
                         "144 after a membership change before SIGKILLing "
                         "stragglers (cover lease timeout + agent grace)")
    args = ap.parse_args()
    if args.elastic:
        if args.num_processes <= 0:
            ap.error("--elastic requires --num-processes (the TARGET "
                     "capacity)")
        ElasticFleet(args).run()
    elif args.num_processes > 0:
        Fleet(args).run()
    else:
        Manager(args).run()


if __name__ == "__main__":
    main()
