#!/usr/bin/env python3
"""Fleet manager: launch training, watch health, recover from preemption.

Equivalent of /root/reference/scripts/run_manager.py:94-146 — creates the TPU,
launches the training subprocess, polls health every 5-10 minutes, and on an
unhealthy (preempted) TPU kills the process group, recreates the TPU, and
relaunches the run (training resumes from its checkpoint + deterministic data
log).  Two health sources:

- TPU health via pluggable shell commands (``--create-cmd``/``--health-cmd``/
  ``--delete-cmd``, e.g. ``gcloud compute tpus tpu-vm ...``; the reference
  hard-coded its TPUServiceAPI).  Empty commands skip TPU management — useful
  when the manager only supervises the process (this container).
- training liveness via the run's ``metrics.jsonl`` heartbeat: if no step is
  logged for ``--stall-timeout`` seconds the run counts as stalled and is
  restarted (the reference had no stall detection).
"""
import argparse
import os
import random
import signal
import subprocess
import sys
import tempfile
import threading
import time
import typing

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


#: training exits with this code after a SIGTERM-triggered emergency
#: checkpoint (homebrewnlp_tpu/run/train_loop.py PREEMPTED_EXIT_CODE — kept
#: as a literal here so the manager never imports jax): a clean preemption,
#: to be relaunched, not a finished or crashed run
PREEMPTED_RC = 143


def sh(cmd: str) -> subprocess.CompletedProcess:
    return subprocess.run(cmd, shell=True, capture_output=True, text=True,
                          timeout=1800)


class Manager:
    def __init__(self, args):
        self.args = args
        # manager log lives with the run artifacts — through the fs seam so
        # remote model_paths (gs://...) work like the reference's GFile log
        # adapter (reference run_manager.py:26-56).  The training
        # subprocess's stdout needs a real fd, so remote paths tee it to a
        # local spool file instead.
        from homebrewnlp_tpu.utils import fs
        if not args.model_path:
            self.log = sys.stderr
        elif fs.is_local(args.model_path):
            os.makedirs(args.model_path, exist_ok=True)
            self.log = open(os.path.join(args.model_path, "run.log"), "a")
        else:
            fs.makedirs(args.model_path)
            self.log = fs.open_(fs.join(args.model_path, "run.log"), "a")

    def out(self, msg: str):
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        self.log.write(f"[{stamp}] {msg}\n")
        self.log.flush()

    def tpu_healthy(self) -> bool:
        if not self.args.health_cmd:
            return True
        r = sh(self.args.health_cmd)
        return r.returncode == 0 and ("READY" in r.stdout or "healthy" in
                                      r.stdout.lower() or not r.stdout.strip())

    def create_tpu(self, recreate: bool = False):
        if recreate and self.args.delete_cmd:
            self.out(f"deleting TPU: {self.args.delete_cmd}")
            sh(self.args.delete_cmd)
            time.sleep(30)
        if self.args.create_cmd:
            self.out(f"creating TPU: {self.args.create_cmd}")
            for attempt in range(20):
                r = sh(self.args.create_cmd)
                if r.returncode == 0:
                    break
                self.out(f"create failed (attempt {attempt}): {r.stderr[-500:]}")
                time.sleep(60)
        # readiness wait with recreate-on-slow (reference :94-109)
        waited = 0
        while not self.tpu_healthy():
            time.sleep(15)
            waited += 15
            if waited > 15 * 15 and self.args.create_cmd:
                self.out("TPU slow to become ready; recreating")
                self.create_tpu(recreate=True)
                return

    def heartbeat_age(self) -> float:
        path = os.path.join(self.args.model_path, "metrics.jsonl") \
            if self.args.model_path else None
        if not path or not os.path.exists(path):
            return 0.0
        # compared against a file mtime, which is epoch wall time — a
        # monotonic clock cannot age it  # graft-lint: allow[wallclock]
        return time.time() - os.path.getmtime(path)

    _spool_path = None
    _spool = None

    def launch(self) -> subprocess.Popen:
        self.out(f"launching: {self.args.run_command}")
        if hasattr(self.log, "fileno"):
            sink = self.log
        else:
            # remote run.log has no fd for subprocess redirection: spool
            # locally, then upload_spool() appends it remotely on every poll
            # tick / restart so crash tracebacks survive VM preemption
            self.upload_spool()
            if self._spool is not None:
                self._spool.close()
            self._spool_path = os.path.join(
                tempfile.gettempdir(), f"run_manager_spool_{os.getpid()}.log")
            self._spool = sink = open(self._spool_path, "w")
        return subprocess.Popen(self.args.run_command, shell=True,
                                stdout=sink, stderr=sink,
                                preexec_fn=os.setsid)

    def upload_spool(self):
        """Append spooled subprocess output to the remote run.log."""
        if self._spool_path is None or not os.path.exists(self._spool_path):
            return
        with open(self._spool_path) as f:
            data = f.read()
        if data:
            self.log.write(data)
            self.log.flush()
        open(self._spool_path, "w").close()  # consumed

    def kill(self, proc: subprocess.Popen,
             grace: typing.Optional[int] = None):
        # SIGTERM now triggers a GRACEFUL stop in training (finish the step,
        # write the emergency checkpoint — potentially minutes for GB-scale
        # state on gs://); a fixed short TERM->KILL gap would tear exactly
        # the checkpoint the preemption path exists to write.  Callers pass
        # a SHORT grace for a wedged (stalled) process that will never
        # honour the graceful flag.
        if grace is None:
            grace = getattr(self.args, "term_grace", 600)
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except ProcessLookupError:
            return
        try:
            proc.wait(timeout=grace)
            return
        except subprocess.TimeoutExpired:
            self.out(f"no exit {grace}s after SIGTERM; escalating to SIGKILL")
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except ProcessLookupError:
            pass

    def run(self):
        self.create_tpu()
        proc = self.launch()
        restarts = 0
        while True:
            time.sleep(self.args.poll_interval
                       + random.randint(0, self.args.poll_jitter))
            self.upload_spool()
            healthy = self.tpu_healthy()
            stalled = (self.args.stall_timeout > 0
                       and self.heartbeat_age() > self.args.stall_timeout)
            rc = proc.poll()  # snapshot once: the process may exit mid-tick
            preempted = rc == PREEMPTED_RC
            if rc is not None and not preempted:
                if healthy:
                    self.out(f"training exited rc={rc}; done")
                    break
                # process died because the TPU went away — fall through
            if preempted:
                # clean, resumable exit: relaunch WITHOUT consuming the
                # crash budget (max_restarts bounds crash loops, and a
                # preemption is not a crash)
                self.out(f"training exited rc={PREEMPTED_RC}: clean "
                         "preemption (emergency checkpoint written); "
                         "relaunching")
            elif healthy and not stalled:
                continue
            else:
                restarts += 1
                if 0 < self.args.max_restarts < restarts:
                    self.out("max restarts exceeded; giving up")
                    break
                self.out(f"unhealthy={not healthy} stalled={stalled}; "
                         f"restarting (#{restarts})")
            # a stalled (wedged) process never honours the graceful flag:
            # don't park the fleet manager on the full checkpoint grace
            self.kill(proc, grace=15 if stalled else None)
            time.sleep(60)
            self.create_tpu(recreate=not healthy)
            proc = self.launch()
        self.upload_spool()
        if self.args.delete_cmd:
            self.out("deleting TPU")
            sh(self.args.delete_cmd)


def _free_port() -> int:
    from homebrewnlp_tpu.distributed.bootstrap import free_port
    return free_port()


class Fleet(Manager):
    """Slice-aware local fan-out (docs/DISTRIBUTED.md): N coordinator-wired
    processes on THIS host — the CPU multiprocess rig, and the shape a
    per-host pod launcher drives one host at a time.

    Each worker gets the explicit-flag bootstrap env
    (``HBNLP_COORDINATOR``/``HBNLP_NUM_PROCESSES``/``HBNLP_PROCESS_ID``,
    homebrewnlp_tpu/distributed/bootstrap.py) plus — on the CPU rig — a
    forced CPU backend with ``--devices-per-process`` virtual devices.
    Output is multiplexed into the manager log with a ``[pN]`` prefix per
    line.

    Restart semantics mirror the single-process manager, fleet-wide:

    - ANY worker exiting 143 = pod-wide preemption (the chief-flag
      broadcast inside the train loop makes every worker stop and write
      the SAME emergency checkpoint) → wait for the rest, relaunch ALL
      without consuming the crash budget.
    - any worker crashing (nonzero, non-143) → its peers are already doomed
      (their next collective would hang on the dead rank) → TERM the rest,
      relaunch ALL, consuming one restart.
    - all zero → done.
    """

    def __init__(self, args):
        super().__init__(args)
        self._pump_threads: typing.List[threading.Thread] = []

    def _pump(self, pid: int, stream):
        """Per-process log prefixing: every worker line lands in the
        manager log as ``[pN] line`` (reader thread per worker — pipes
        would deadlock on a filled buffer otherwise)."""
        for line in iter(stream.readline, ""):
            self.out(f"[p{pid}] {line.rstrip()}")
        stream.close()

    def launch_fleet(self) -> typing.List[subprocess.Popen]:
        n = self.args.num_processes
        port = _free_port()  # fresh per generation: no TIME_WAIT rebind race
        self.out(f"launching fleet: {n} processes, coordinator "
                 f"localhost:{port}: {self.args.run_command}")
        procs = []
        for pid in range(n):
            env = dict(os.environ,
                       HBNLP_COORDINATOR=f"localhost:{port}",
                       HBNLP_NUM_PROCESSES=str(n),
                       HBNLP_PROCESS_ID=str(pid))
            if self.args.cpu_rig:
                import re
                flags = re.sub(
                    r"--xla_force_host_platform_device_count=\d+", "",
                    env.get("XLA_FLAGS", ""))
                env["JAX_PLATFORMS"] = "cpu"
                env["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count="
                    f"{self.args.devices_per_process}")
            p = subprocess.Popen(self.args.run_command, shell=True, env=env,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True,
                                 preexec_fn=os.setsid)
            t = threading.Thread(target=self._pump, args=(pid, p.stdout),
                                 daemon=True)
            t.start()
            self._pump_threads.append(t)
            procs.append(p)
        return procs

    def kill_fleet(self, procs, grace: typing.Optional[int] = None):
        for p in procs:
            if p.poll() is None:
                self.kill(p, grace=grace)

    def run(self):
        procs = self.launch_fleet()
        restarts = 0
        while True:
            time.sleep(self.args.poll_interval
                       + random.randint(0, self.args.poll_jitter))
            rcs = [p.poll() for p in procs]
            stalled = (self.args.stall_timeout > 0
                       and self.heartbeat_age() > self.args.stall_timeout)
            if all(rc is None for rc in rcs) and not stalled:
                continue
            preempted = any(rc == PREEMPTED_RC for rc in rcs)
            crashed = any(rc not in (None, 0, PREEMPTED_RC) for rc in rcs)
            if not preempted and not crashed and not stalled \
                    and any(rc is None for rc in rcs):
                # staggered CLEAN finish: some workers exited 0 while the
                # chief is still flushing final artifacts (telemetry dump,
                # async-checkpoint close on slow storage) — keep waiting;
                # a worker that never finishes is the stall detector's job
                continue
            if preempted:
                # clean pod-wide preemption: peers agreed via the chief-flag
                # broadcast — give stragglers the full checkpoint grace
                # before escalating, then relaunch WITHOUT consuming budget
                self.out(f"fleet preempted (rcs={rcs}): waiting for peers, "
                         "then relaunching")
                deadline = time.monotonic() + getattr(
                    self.args, "term_grace", 600)
                while any(p.poll() is None for p in procs) \
                        and time.monotonic() < deadline:
                    time.sleep(1)
                self.kill_fleet(procs, grace=15)
            elif all(rc == 0 for rc in rcs):
                self.out("fleet finished cleanly; done")
                break
            else:
                # crash or stall: a dead rank hangs every peer's next
                # collective — tear the whole generation down and relaunch
                restarts += 1
                if 0 < self.args.max_restarts < restarts:
                    self.out(f"fleet rcs={rcs} stalled={stalled}; max "
                             "restarts exceeded; giving up")
                    self.kill_fleet(procs, grace=15)
                    return
                self.out(f"fleet unhealthy (rcs={rcs} stalled={stalled}); "
                         f"restarting (#{restarts})")
                self.kill_fleet(procs, grace=15 if stalled else None)
            time.sleep(self.args.restart_delay)
            procs = self.launch_fleet()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("run_command", help="training command to supervise")
    ap.add_argument("--model-path", default="", help="run dir (logs, heartbeat)")
    ap.add_argument("--create-cmd", default="", help="shell cmd creating the TPU")
    ap.add_argument("--health-cmd", default="", help="shell cmd checking TPU health")
    ap.add_argument("--delete-cmd", default="", help="shell cmd deleting the TPU")
    ap.add_argument("--poll-interval", type=int, default=300)
    ap.add_argument("--poll-jitter", type=int, default=300)
    ap.add_argument("--stall-timeout", type=int, default=3600)
    ap.add_argument("--term-grace", type=int, default=600, dest="term_grace",
                    help="seconds to wait after SIGTERM for the training "
                         "process to finish its emergency checkpoint "
                         "before SIGKILL")
    ap.add_argument("--max-restarts", type=int, default=0, help="0 = unlimited")
    ap.add_argument("--num-processes", type=int, default=0,
                    dest="num_processes",
                    help="fan out N coordinator-wired local processes "
                         "(docs/DISTRIBUTED.md); 0 = supervise run_command "
                         "as a single process (the per-host pod shape)")
    ap.add_argument("--devices-per-process", type=int, default=1,
                    dest="devices_per_process",
                    help="virtual CPU devices per fanned-out process "
                         "(--cpu-rig only)")
    ap.add_argument("--cpu-rig", action="store_true", default=True,
                    dest="cpu_rig",
                    help="force JAX_PLATFORMS=cpu + virtual devices in the "
                         "fleet (default; --no-cpu-rig passes the "
                         "environment through for accelerator hosts)")
    ap.add_argument("--no-cpu-rig", action="store_false", dest="cpu_rig")
    ap.add_argument("--restart-delay", type=int, default=5,
                    dest="restart_delay",
                    help="seconds between fleet teardown and relaunch")
    args = ap.parse_args()
    if args.num_processes > 0:
        Fleet(args).run()
    else:
        Manager(args).run()


if __name__ == "__main__":
    main()
