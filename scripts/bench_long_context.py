#!/usr/bin/env python3
"""Long-context single-chip benchmark: tokens/sec AND MFU at seq 16,384.

The BASELINE.md 'Long context' row (4,037 tok/s rounds 1-2) reported
throughput without MFU, and its "flash attention dominates" was asserted,
not measured (VERDICT r3 weak #1).  This script is the standing measurement:
seq 16,384 / d1024 / depth 16 / dot-product causal attention / revnet +
scan-over-layers, batch 1, bf16 — the flagship long-context recipe shrunk
onto one chip — reporting tokens/sec/chip and MFU (3x-forward convention,
homebrewnlp_tpu/utils/flops.py), with ``--bwd {pallas,xla}`` to A/B the
flash-attention backward (HBNLP_FLASH_BWD_XLA routes the kept XLA-scan
path).

Usage (real chip):  python scripts/bench_long_context.py [--bwd pallas|xla]
Prints ONE JSON line like bench.py.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

LC_CONFIG = {
    "model_mode": "gpt", "use_video": False, "use_language": True,
    "sequence_length": 16384, "features_per_head": 128, "heads": 8,
    "depth": 16, "train_batch_size": 1, "vocab_size": 256,
    "calc_accuracy": False, "memory_reduction_strategy": "revnet",
    "block_config": [
        {"layer": ["norm-shift-scale-features-group",
                   "bottleneck_group_linear-in:relu-mid:relu-mid:norm-mid:shift-mid:scale-mid:features"]},
        {"layer": ["norm-shift-scale-features-group",
                   "attention-dot_product-context-in:relu"]}],
    "group_linear_factor": 2,
    "intermediate_feed_forward_multiplier_multiplier": 0.5,
    "optimizer": "adaptive_clip:0.003-adam-learning_rate",
    "learning_rate": 0.003, "weight_decay": 0.0001,
    "learning_rate_config": {"linear_warmup": {"final_step": 2000}},
    "calculation_dtype": "bfloat16", "storage_dtype": "bfloat16",
    "optimizer_slice_dtype": "float32", "slice_dtype": "float32",
    "scan_layers": True, "use_flash_attention": True,
    # stash_attention_outputs intentionally NOT set: the "auto" default
    # must enable it here itself (~545MB of (out, lse) residents at 16k —
    # model/blocks.py resolve_stash) — this bench is the standing proof
    # that the shipped defaults reproduce the measured numbers
    "use_checkpointing": False, "macro_batching": 1,
    "model_path": "/tmp/bench_long_context",
}

WARMUP_STEPS = 2
MEASURE_STEPS = 5


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--bwd", choices=["pallas", "xla"], default="pallas",
                    help="flash-attention backward: pallas kernels (default)"
                         " or the kept XLA-scan fallback")
    ap.add_argument("--seq", type=int, default=16384)
    args = ap.parse_args()
    out = run(bwd=args.bwd, seq=args.seq)
    print(json.dumps(out))
    return 0


def run(bwd: str = "pallas", seq: int = 16384) -> dict:
    """Measure and return the result dict (bench.py rides these keys on its
    headline JSON line; the CLI path prints them)."""
    if bwd == "xla":
        os.environ["HBNLP_FLASH_BWD_XLA"] = "1"

    import numpy as np
    import jax
    import jax.numpy as jnp
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer

    cfg = dict(LC_CONFIG, sequence_length=seq)
    if jax.default_backend() == "cpu":
        cfg.update(sequence_length=min(seq, 2048), depth=2,
                   features_per_head=64, heads=2,
                   calculation_dtype="float32", storage_dtype="float32")

    params = ModelParameter(cfg)
    model = Model(params)
    trainer = Trainer(params, model)
    rng = np.random.default_rng(0)

    def make_batch():
        x = rng.integers(0, params.vocab_size,
                         (params.train_batch_size, params.sequence_length, 1))
        return {"token_x": jnp.asarray(x),
                "token_y": jnp.asarray((x + 1) % params.vocab_size)}

    t0 = time.monotonic()
    state = trainer.init_state(make_batch())
    print(f"setup {time.monotonic() - t0:.1f}s; compiling...", file=sys.stderr)
    t0 = time.monotonic()
    for _ in range(WARMUP_STEPS):
        state, metrics = trainer.step(state, make_batch())
    float(metrics["loss"])  # force the dispatched chain to completion
    print(f"compile+warmup {time.monotonic() - t0:.1f}s", file=sys.stderr)

    batches = [make_batch() for _ in range(MEASURE_STEPS)]
    t0 = time.monotonic()
    for batch in batches:
        state, metrics = trainer.step(state, batch)
    final_loss = float(metrics["loss"])
    dt = time.monotonic() - t0

    tokens = MEASURE_STEPS * params.train_batch_size * params.sequence_length
    n_chips = max(1, len(jax.devices()))
    tok_s = tokens / dt / n_chips

    try:
        from homebrewnlp_tpu.utils.flops import forward_flops_split, mfu
        fwd, fwd_exec = forward_flops_split(
            lambda v, b: trainer.model.apply(v, b).total_loss.data,
            state.variables, batches[0])
        # two conventions, one timing: full-square (dead causal cells count
        # as useful — stable round-over-round) and causal/executed (dead
        # cells excluded — the honest kernel-work denominator)
        mfu_frac = round(mfu(fwd, dt / MEASURE_STEPS, n_chips), 4)
        mfu_causal = round(mfu(fwd_exec, dt / MEASURE_STEPS, n_chips), 4)
    except Exception as exc:
        print(f"MFU computation failed: {exc}", file=sys.stderr)
        mfu_frac = mfu_causal = None

    print(f"final loss {final_loss:.4f}", file=sys.stderr)
    out = {"metric": f"LM tokens/sec/chip @ {params.sequence_length}-ctx "
                     "long-context",
           "value": round(tok_s, 2), "unit": "tokens/sec/chip",
           "flash_bwd": bwd}
    if mfu_frac is not None:
        out["mfu"] = mfu_frac
    if mfu_causal is not None and mfu_causal != mfu_frac:
        out["mfu_causal"] = mfu_causal
    return out


if __name__ == "__main__":
    sys.exit(main())
