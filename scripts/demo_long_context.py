"""Demonstrate the 1b_long_context target semantics on the 8-device CPU mesh.

Runs a width-reduced configs/1b_long_context.json — SAME sequence length
(32768), SAME sequence_parallel=8 sharding, block structure, revnet memory
strategy, and optimizer family; reduced width/depth so the demo finishes on
CPU — for a few steps and reports the losses.  Before the ring-attention
custom_vjp backward (parallel/ring_attention.py), autodiff stored the
per-hop [sq, sq] probability tensors: at the full config's shapes ~69 GB of
residuals per layer-block, which no chip holds; at THIS demo's shapes it
would still stash 8 x [1, 4, 4096, 4096] f32 = 2.1 GB per attention layer,
where the blockwise backward needs O(block_q x sq) transients.

Usage:
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python scripts/demo_long_context.py [--steps N]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    args = ap.parse_args()

    import jax
    import numpy as np

    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.core import sharding as shardlib
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer

    with open(os.path.join(os.path.dirname(__file__), "..",
                           "configs", "1b_long_context.json")) as f:
        cfg = json.load(f)
    # width/depth-reduced, same 32k x sp=8 shape; CPU-bf16 is slow, use f32
    cfg.update({"features_per_head": 64, "heads": 4, "depth": 2,
                "train_batch_size": 1, "vocab_size": 256,
                "calculation_dtype": "float32", "storage_dtype": "float32",
                "slice_dtype": "float32", "optimizer_slice_dtype": "float32",
                "use_checkpointing": False, "macro_batching": 1,
                "tpu_size": 8})
    params = ModelParameter(cfg)
    assert params.sequence_length == 32768
    assert params.mesh_shape.get(shardlib.SEQUENCE_AXIS) == 8
    mesh = shardlib.build_mesh(params)
    print(f"mesh: {dict(mesh.shape)} devices={len(jax.devices())}")

    rng = np.random.default_rng(0)
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    batch = {"token_x": x, "token_y": (x + 1) % params.vocab_size}

    model = Model(params)
    trainer = Trainer(params, model, mesh=mesh)
    state = trainer.init_state(batch)
    n_params = sum(int(np.prod(v.shape)) for v in state.variables.values())
    print(f"params: {n_params:,}  seq={params.sequence_length} "
          f"sp={params.mesh_shape[shardlib.SEQUENCE_AXIS]}")

    losses = []
    for i in range(args.steps):
        t0 = time.monotonic()
        state, metrics = trainer.step(state, batch, jax.random.PRNGKey(i))
        loss = float(metrics["loss"])
        losses.append(loss)
        print(f"step {i}: loss={loss:.4f}  wall={time.monotonic() - t0:.1f}s",
              flush=True)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    print("OK: 32k-sequence sp=8 training to finite, decreasing loss")


if __name__ == "__main__":
    main()
