#!/usr/bin/env python3
"""Video -> TFRecord dataset builder (local files).

Equivalent of the reference's /root/reference/scripts/video2tfrecord.py proto
layout: one record per frame with features ``frame`` (encoded JPEG),
``concat`` (1 on the first frame of each new clip), ``skip_frame`` and —
with --captions — ``tokens`` + ``mask`` (token count valid for the frame).
The reference additionally streamed from YouTube with proxy rotation and
aligned VTT subtitles word-by-word (:57-343); this zero-egress variant takes
local video files (anything cv2 opens) and optional per-video caption .txt
files, tokenised byte-level or with a tokenizer.json.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example  # noqa: E402


def _tokens_for(text: str, n: int, tokenizer):
    if tokenizer is not None:
        ids = tokenizer.encode(text).ids
    else:
        ids = list(text.encode("utf-8", "replace"))
    ids = ids[:n]
    mask = len(ids)
    return ids + [0] * (n - len(ids)), mask


def main():
    import cv2
    ap = argparse.ArgumentParser()
    ap.add_argument("videos", nargs="+")
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--prefix", default="vid")
    ap.add_argument("--fps", type=float, default=1.0, help="sampled frames/sec")
    ap.add_argument("--width", type=int, default=320)
    ap.add_argument("--height", type=int, default=176)
    ap.add_argument("--frames-per-file", type=int, default=4096)
    ap.add_argument("--captions", action="store_true",
                    help="read <video>.txt captions into tokens/mask")
    ap.add_argument("--language-tokens-per-frame", type=int, default=64)
    ap.add_argument("--tokenizer", default="", help="optional tokenizer.json")
    args = ap.parse_args()

    tokenizer = None
    if args.tokenizer:
        from tokenizers import Tokenizer
        tokenizer = Tokenizer.from_file(args.tokenizer)

    os.makedirs(args.output_dir, exist_ok=True)
    file_idx = 0
    writer = None
    frames_in_file = 0

    def new_writer():
        nonlocal writer, file_idx, frames_in_file
        if writer is not None:
            writer.close()
        path = os.path.join(args.output_dir,
                            f"{args.prefix}_{file_idx:05d}_{args.frames_per_file}.tfrecord")
        writer = RecordWriter(path)
        file_idx += 1
        frames_in_file = 0
        print(f"writing {path}")

    new_writer()
    for video_path in args.videos:
        cap = cv2.VideoCapture(video_path)
        src_fps = cap.get(cv2.CAP_PROP_FPS) or 25.0
        stride = max(1, int(round(src_fps / args.fps)))
        caption = ""
        cap_path = os.path.splitext(video_path)[0] + ".txt"
        if args.captions and os.path.exists(cap_path):
            caption = open(cap_path, errors="ignore").read()
        i = 0
        first = True
        while True:
            ok, frame = cap.read()
            if not ok:
                break
            if i % stride:
                i += 1
                continue
            i += 1
            frame = cv2.resize(frame, (args.width, args.height))
            ok, enc = cv2.imencode(".jpg", frame,
                                   [cv2.IMWRITE_JPEG_QUALITY, 95])
            if not ok:
                continue
            features = {"frame": enc.tobytes(),
                        "concat": [1 if first else 0],
                        "skip_frame": [0]}
            if args.captions:
                toks, mask = _tokens_for(caption, args.language_tokens_per_frame,
                                         tokenizer)
                features["tokens"] = toks
                features["mask"] = [mask]
            writer.write(encode_example(features))
            first = False
            frames_in_file += 1
            if frames_in_file >= args.frames_per_file:
                new_writer()
        cap.release()
    writer.close()


if __name__ == "__main__":
    main()
