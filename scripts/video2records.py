#!/usr/bin/env python3
"""Video -> TFRecord dataset builder (local files).

Equivalent of the reference's /root/reference/scripts/video2tfrecord.py proto
layout: one record per frame with features ``frame`` (encoded JPEG),
``concat`` (1 on the first frame of each new clip), ``skip_frame`` and —
with text — ``tokens`` + ``mask`` (token count valid for the frame).

Text sources, in precedence order per video:

* ``<video>.vtt`` — WebVTT subtitles: word timestamps are aligned to tokens
  per frame exactly like the reference (decode_vtt + bpe_with_word_split +
  the worker frame loop, video2tfrecord.py:186-361,684-707): tokens of all
  words falling in a sampled frame's interval chunk into groups of
  ``ltp - 1``; the first group rides the real frame, overflow groups ride
  black padding frames flagged ``skip_frame``; ``mask`` counts real tokens.
* ``<video>.txt`` — whole-video caption, truncated to one frame's tokens
  (with --captions).

The reference additionally streamed from YouTube with proxy rotation; this
zero-egress variant takes local video files (anything cv2 opens), tokenised
byte-level or with a tokenizer.json.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example  # noqa: E402
from homebrewnlp_tpu.data import vtt as vtt_mod  # noqa: E402


def _tokens_for(text: str, n: int, tokenizer):
    if tokenizer is not None:
        ids = tokenizer.encode(text).ids
    else:
        ids = list(text.encode("utf-8", "replace"))
    ids = ids[:n]
    mask = len(ids)
    return ids + [0] * (n - len(ids)), mask


def _make_codec(tokenizer):
    if tokenizer is not None:
        return (lambda t: tokenizer.encode(t).ids,
                lambda ids: tokenizer.decode(ids))
    return (lambda t: list(t.encode("utf-8", "replace")),
            lambda ids: bytes(ids).decode("utf-8", "replace"))


def main():
    import cv2
    ap = argparse.ArgumentParser()
    ap.add_argument("videos", nargs="+")
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--prefix", default="vid")
    ap.add_argument("--fps", type=float, default=1.0, help="sampled frames/sec")
    ap.add_argument("--width", type=int, default=320)
    ap.add_argument("--height", type=int, default=176)
    ap.add_argument("--frames-per-file", type=int, default=4096)
    ap.add_argument("--captions", action="store_true",
                    help="read <video>.txt captions into tokens/mask")
    ap.add_argument("--subtitles", action="store_true",
                    help="align <video>.vtt word timestamps to per-frame "
                         "tokens (reference video2tfrecord semantics)")
    ap.add_argument("--language-tokens-per-frame", type=int, default=64)
    ap.add_argument("--padding-token", type=int, default=None,
                    help="default: 50257 with --tokenizer (GPT-2 style pad "
                         "id), 0 for the byte-level fallback (vocab 256)")
    ap.add_argument("--tokenizer", default="", help="optional tokenizer.json")
    args = ap.parse_args()

    tokenizer = None
    if args.tokenizer:
        from tokenizers import Tokenizer
        tokenizer = Tokenizer.from_file(args.tokenizer)
    if args.padding_token is None:
        args.padding_token = 50257 if tokenizer is not None else 0
    if args.subtitles and args.language_tokens_per_frame < 2:
        ap.error("--subtitles needs --language-tokens-per-frame >= 2 "
                 "(one slot is reserved for chunking)")

    os.makedirs(args.output_dir, exist_ok=True)
    file_idx = 0
    writer = None
    frames_in_file = 0

    def new_writer():
        nonlocal writer, file_idx, frames_in_file
        if writer is not None:
            writer.close()
        path = os.path.join(args.output_dir,
                            f"{args.prefix}_{file_idx:05d}_{args.frames_per_file}.tfrecord")
        writer = RecordWriter(path)
        file_idx += 1
        frames_in_file = 0
        print(f"writing {path}")

    import numpy as np
    new_writer()
    ltp = args.language_tokens_per_frame
    ok_pad, pad_jpg = cv2.imencode(
        ".jpg", np.zeros((args.height, args.width, 3), np.uint8))
    assert ok_pad
    pad_jpg = pad_jpg.tobytes()

    def emit(features):
        nonlocal frames_in_file
        writer.write(encode_example(features))
        frames_in_file += 1
        if frames_in_file >= args.frames_per_file:
            new_writer()

    for video_path in args.videos:
        cap = cv2.VideoCapture(video_path)
        src_fps = cap.get(cv2.CAP_PROP_FPS) or 25.0
        stride = max(1, int(round(src_fps / args.fps)))
        caption = ""
        cap_path = os.path.splitext(video_path)[0] + ".txt"
        if args.captions and os.path.exists(cap_path):
            caption = open(cap_path, errors="ignore").read()
        bpe_list, stamps, vtt_state = None, None, {}
        vtt_path = os.path.splitext(video_path)[0] + ".vtt"
        if args.subtitles and os.path.exists(vtt_path):
            text, words, stamps = vtt_mod.decode_vtt(
                open(vtt_path, errors="ignore").read())
            enc_fn, dec_fn = _make_codec(tokenizer)
            bpe_list = vtt_mod.split_tokens_on_words(enc_fn, dec_fn, words, text)
        i = 0
        first = True
        while True:
            ok, frame = cap.read()
            if not ok:
                break
            if i % stride:
                i += 1
                continue
            frame_end_s = (i + stride) / src_fps
            i += 1
            frame = cv2.resize(frame, (args.width, args.height))
            ok, enc = cv2.imencode(".jpg", frame,
                                   [cv2.IMWRITE_JPEG_QUALITY, 95])
            if not ok:
                continue
            if bpe_list is not None:
                # word-timestamp alignment: first token group rides the real
                # frame, overflow groups ride padding frames (skip_frame=1)
                groups = vtt_mod.frames_token_groups(
                    bpe_list, stamps, frame_end_s, ltp, args.padding_token,
                    vtt_state)
                for toks, mask, skip in groups:
                    emit({"frame": pad_jpg if skip else enc.tobytes(),
                          "concat": [1 if (first and not skip) else 0],
                          "skip_frame": [1 if skip else 0],
                          "tokens": toks, "mask": [mask]})
                    first = False
                continue
            features = {"frame": enc.tobytes(),
                        "concat": [1 if first else 0],
                        "skip_frame": [0]}
            if args.captions:
                toks, mask = _tokens_for(caption, ltp, tokenizer)
                features["tokens"] = toks
                features["mask"] = [mask]
            emit(features)
            first = False
        cap.release()
    writer.close()


if __name__ == "__main__":
    main()
