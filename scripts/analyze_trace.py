#!/usr/bin/env python3
"""Summarise a jax.profiler trace into a per-op / per-category time table.

Usage:
    python scripts/analyze_trace.py <trace_dir_or_trace.json.gz> [--steps N]
                                    [--top K]

Works on the ``plugins/profile/<ts>/*.trace.json.gz`` files that
``jax.profiler.start_trace`` writes (the train loop's ``profile_steps``
option, run/train_loop.py; SIGUSR2 on-demand captures land in the same
format).  Device-side XLA events carry the HLO instruction name in
``args.hlo_op`` — that is the selection rule here, replacing the fragile
name-prefix heuristics the round-1/2 analyses used (kept only as a
fallback for traces predating the ``hlo_op`` args).  For per-model-SCOPE
attribution (which block spent the time, joined against the cost ledger)
use ``scripts/attribute_step.py``.

A trace with zero device-side events fails LOUDLY (nonzero exit naming the
file) instead of printing an empty table — an empty capture window or a
host-only trace must not read as "nothing is slow".
"""
import argparse
import collections
import glob
import gzip
import json
import os
import sys


def resolve_trace_file(path: str) -> str:
    """The actual ``*.trace.json.gz`` behind ``path`` (dir or file) — named
    in every error so a bad capture is diagnosable."""
    if os.path.isdir(path):
        hits = sorted(glob.glob(os.path.join(
            path, "**", "*.trace.json.gz"), recursive=True))
        if not hits:
            raise SystemExit(f"no *.trace.json.gz under {path}")
        return hits[-1]
    return path


def load_events(path: str):
    """Every complete ('X') event with a duration from the newest trace
    file under ``path``."""
    trace_file = resolve_trace_file(path)
    with gzip.open(trace_file) as f:
        trace = json.load(f)
    return [e for e in trace.get("traceEvents", [])
            if e.get("ph") == "X" and e.get("dur")]


def device_events(events):
    """The device-side XLA op events: those carrying ``args.hlo_op`` (the
    HLO instruction name) — the reliable selector on every backend this
    rig profiles."""
    return [e for e in events
            if isinstance(e.get("args"), dict) and e["args"].get("hlo_op")]


def categorize(name: str) -> str:
    if "dynamic-update-slice" in name or "dynamic_update" in name:
        return "scan-stack (DUS)"
    if "dynamic-slice" in name or "dynamic_slice" in name:
        return "scan-unstack (DS)"
    if "convert_reduce" in name or name.startswith("reduce"):
        return "reduce"
    if "add_add" in name or "select_add" in name or \
            name.startswith(("add_", "select_")):
        return "adds/elementwise"
    if "convert_bitcast" in name or name.startswith(
            ("convert", "bitcast", "copy", "transpose")):
        return "convert/copy/transpose"
    if name.startswith("fusion"):
        # unprefixed fusion.N instructions are XLA's output/dot fusions
        return "fusion (dot-rooted)"
    if "fusion" in name:
        # loop_fusion/input_fusion are elementwise/reduce bodies — lumping
        # them with dot fusions would overstate matmul time and hide
        # elementwise overhead (op-named CPU fusions like
        # convert_bitcast_fusion land in the branches above)
        return "fusion (loop/elementwise)"
    return "other: " + name.split(".")[0].split("(")[0][:32]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace", help="trace dir or *.trace.json.gz")
    ap.add_argument("--steps", type=int, default=1,
                    help="traced step count (per-step normalisation)")
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args()

    trace_file = resolve_trace_file(args.trace)
    evs = load_events(args.trace)
    if not evs:
        raise SystemExit(f"{trace_file}: trace contains zero timed events "
                         "— empty capture window?")
    dev = device_events(evs)
    if dev:
        # device events name their HLO op exactly — but the window can
        # include other jitted programs (a warm-up compile, an interleaved
        # eval) whose one-off events would inflate ms/step: keep only the
        # DOMINANT module's events, and ops seen at least once per step
        # (attribute_step.py applies the same module discipline via
        # ENTRY_MODULES)
        mod_time = collections.Counter()
        for e in dev:
            mod_time[e["args"].get("hlo_module", "")] += e["dur"]
        top_mod = mod_time.most_common(1)[0][0]
        skipped = len(mod_time) - 1
        if skipped:
            print(f"note: keeping module {top_mod!r}; ignoring {skipped} "
                  "other module(s) in the window "
                  f"({', '.join(sorted(m for m in mod_time if m != top_mod))})")
        named = [(e["args"]["hlo_op"], e["dur"]) for e in dev
                 if e["args"].get("hlo_module", "") == top_mod]
        cnt_all = collections.Counter(n for n, _ in named)

        def keep(name: str) -> bool:
            return cnt_all[name] >= args.steps
    else:
        # legacy traces without hlo_op args: the old name heuristics —
        # wrapper/marker events are python frames, pjit spans, and the bare
        # per-step queue markers ("2"/"5"/"8" in those traces)
        named = [(e["name"], e["dur"]) for e in evs]
        prefix_skip = ("jit_", "Pjit", "$", "np.", "while",
                       "ThreadpoolListener", "Tfrt", "ParseArguments",
                       "ThunkExecutor")
        exact_skip = {"2", "5", "8"}
        cnt_all = collections.Counter(n for n, _ in named)

        def keep(name: str) -> bool:
            return (cnt_all[name] >= args.steps
                    and not name.startswith(prefix_skip)
                    and name not in exact_skip)

    agg = collections.Counter()
    cnt = collections.Counter()
    for name, dur in named:
        if keep(name):
            agg[name] += dur
            cnt[name] += 1
    if not agg:
        raise SystemExit(
            f"{trace_file}: trace contains zero device-side events "
            "(no args.hlo_op and nothing past the legacy filters) — "
            "was the capture window empty, or host-only?")

    print(f"== top ops (us summed over trace; /{args.steps} steps) ==")
    for i, (name, dur) in enumerate(agg.most_common(args.top)):
        print(f"{dur / 1e3 / args.steps:10.2f} ms/step  x{cnt[name]:6d}  "
              f"{name[:90]}")

    cats = collections.Counter()
    for name, dur in agg.items():
        cats[categorize(name)] += dur
    total = sum(cats.values())
    print(f"\n== categories ({total / 1e3 / args.steps:.1f} ms/step "
          f"categorized) ==")
    for cat, dur in cats.most_common(15):
        print(f"{dur / 1e3 / args.steps:10.2f} ms/step  "
              f"{dur / total * 100:5.1f}%  {cat}")
    print("\nper-model-scope attribution (time vs FLOPs vs bytes share): "
          f"python scripts/attribute_step.py {args.trace}")


if __name__ == "__main__":
    main()
