#!/usr/bin/env python3
"""Summarise a jax.profiler trace into a per-op / per-category time table.

Usage:
    python scripts/analyze_trace.py <trace_dir_or_trace.json.gz> [--steps N]
                                    [--top K]

Works on the ``plugins/profile/<ts>/*.trace.json.gz`` files that
``jax.profiler.start_trace`` writes (the train loop's ``profile_steps``
option, run/train_loop.py).  The tensorboard profile plugin's converters are
broken against this image's TF, and XLA dump flags don't reach the
tunnel-side compiler — parsing the chrome-trace events by name is the
methodology that produced the round-1/2 analyses in docs/PERFORMANCE.md
(SURVEY.md §5.1: the reference had no op-level profiling at all).
"""
import argparse
import collections
import glob
import gzip
import json
import os


def load_events(path: str):
    if os.path.isdir(path):
        hits = sorted(glob.glob(os.path.join(
            path, "**", "*.trace.json.gz"), recursive=True))
        if not hits:
            raise SystemExit(f"no *.trace.json.gz under {path}")
        path = hits[-1]
    with gzip.open(path) as f:
        trace = json.load(f)
    return [e for e in trace["traceEvents"]
            if e.get("ph") == "X" and e.get("dur")]


def categorize(name: str) -> str:
    if "dynamic-update-slice" in name or "dynamic_update" in name:
        return "scan-stack (DUS)"
    if "dynamic-slice" in name or "dynamic_slice" in name:
        return "scan-unstack (DS)"
    if "convert_reduce" in name or name.startswith("reduce"):
        return "reduce"
    if "add_add" in name or "select_add" in name or \
            name.startswith(("add_", "select_")):
        return "adds/elementwise"
    if "convert_bitcast" in name or name.startswith(
            ("convert", "bitcast", "copy", "transpose")):
        return "convert/copy/transpose"
    if name.startswith("fusion"):
        return "fusion (dot-rooted)"
    return "other: " + name.split(".")[0].split("(")[0][:32]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace", help="trace dir or *.trace.json.gz")
    ap.add_argument("--steps", type=int, default=1,
                    help="traced step count (per-step normalisation)")
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args()

    evs = load_events(args.trace)
    agg = collections.Counter()
    cnt = collections.Counter()
    for e in evs:
        agg[e["name"]] += e["dur"]
        cnt[e["name"]] += 1

    # wrapper/marker events, not device ops: python frames, pjit spans, and
    # the bare per-step queue markers ("2"/"5"/"8" in these traces)
    prefix_skip = ("jit_", "Pjit", "$", "np.", "while")
    exact_skip = {"2", "5", "8"}

    def keep(name: str) -> bool:
        return (cnt[name] >= args.steps
                and not name.startswith(prefix_skip)
                and name not in exact_skip)

    print(f"== top ops (us summed over trace; /{args.steps} steps) ==")
    shown = 0
    for name, dur in agg.most_common():
        if not keep(name):
            continue
        print(f"{dur / 1e3 / args.steps:10.2f} ms/step  x{cnt[name]:6d}  "
              f"{name[:90]}")
        shown += 1
        if shown >= args.top:
            break

    cats = collections.Counter()
    for name, dur in agg.items():
        if not keep(name):
            continue
        cats[categorize(name)] += dur
    total = sum(cats.values())
    print(f"\n== categories ({total / 1e3 / args.steps:.1f} ms/step "
          f"categorized) ==")
    for cat, dur in cats.most_common(15):
        print(f"{dur / 1e3 / args.steps:10.2f} ms/step  "
              f"{dur / total * 100:5.1f}%  {cat}")


if __name__ == "__main__":
    main()
