#!/usr/bin/env python3
"""AOT pod lowering: compile a config's FULL training step against a detached
TPU topology and report per-chip memory + the collective inventory.

The reference could at least *launch* its flagship on the pod it targeted
(/root/reference/src/main.py:107-147 resolves the real TPU topology before
building the graph); this is the TPU-native, stronger equivalent without pod
hardware: jax AOT compilation against a ``TopologyDescription``
(jax.experimental.topologies) runs the real XLA/Mosaic TPU compiler for the
target chip generation, partitions the step across the full device mesh
(GSPMD + shard_map ring attention), and reports exact per-chip buffer sizes
(``Compiled.memory_analysis()``) plus every cross-chip collective in the
final HLO.  If the config does not fit its pod, this fails loudly — without
burning a pod-hour.

Usage:
  python scripts/pod_lowering.py                      # both standard targets
  python scripts/pod_lowering.py --config configs/1b_long_context.json \
      --topology v5p:4x4x8 [--hbm-gb 95]

Prints one JSON report per target; non-zero exit if any target exceeds HBM.
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import re
import sys
import time
import typing

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

# v5p HBM per chip (95 GiB usable of 96); v5e is 16
HBM_BYTES = {"v5p": 95 * 1024 ** 3, "v5e": 15.75 * 1024 ** 3}

STANDARD_TARGETS = [
    # (config, topology, expected devices, HBM key) — the 1B long-context
    # target at its configured tpu_size 128 (BASELINE.json configs[4]) and
    # the flagship at tpu_size 64 (VERDICT r4 next-round #1)
    ("configs/1b_long_context.json", "v5p:4x4x8", 128, "v5p", {}),
    ("configs/32big_mixer.json", "v5p:4x4x4", 64, "v5p", {"tpu_size": 64}),
]


def _patch_cheap_init():
    """Replace the numpy QR/normal initializers with zeros for the lowering:
    AOT compilation consumes only shapes/dtypes/shardings, and the QR
    orthogonalisation of d8192 matrices costs minutes of host time that
    buys nothing here.  Returns an undo function."""
    from homebrewnlp_tpu.model import backend

    saved = (backend.OrthogonalInit.__call__, backend.NormalInit.__call__)

    def zeros_orth(self, rng, sizes):
        import numpy as np
        return np.zeros(sizes, np.float32)

    def zeros_normal(self, rng, sizes):
        import numpy as np
        return np.zeros(sizes, np.float32)

    backend.OrthogonalInit.__call__ = zeros_orth
    backend.NormalInit.__call__ = zeros_normal

    def undo():
        backend.OrthogonalInit.__call__, backend.NormalInit.__call__ = saved

    return undo


def _opt_state_avals(optimizer, var_avals, mesh):
    """Optimizer slot avals via the REAL ``Optimizer.init`` slot discovery,
    with materialisation swapped for ShapeDtypeStructs (``_zeros_for``'s
    sharding rule: same-shape slots inherit the variable's sharding,
    reduced-shape slots replicate)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    from homebrewnlp_tpu import optim as optim_mod

    saved = optim_mod._zeros_for

    def aval_zeros(variable, shape, dtype):
        sharding = getattr(variable, "sharding", None)
        if sharding is None or tuple(shape) != tuple(variable.shape):
            sharding = NamedSharding(mesh, PartitionSpec())
        return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sharding)

    optim_mod._zeros_for = aval_zeros
    try:
        return optimizer.init(var_avals)
    finally:
        optim_mod._zeros_for = saved


def _collective_inventory(hlo: str) -> typing.Dict[str, dict]:
    """Count + size every cross-partition collective in the compiled HLO."""
    dtype_bytes = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
                   "f64": 8, "s8": 1, "u8": 1, "pred": 1, "s64": 8, "u64": 8}
    inv: typing.Dict[str, dict] = collections.defaultdict(
        lambda: {"count": 0, "bytes_moved": 0})
    pat = re.compile(
        r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
        r"all-to-all)(?:-start)?\b")
    shape_pat = re.compile(r"(\w+)\[([\d,]*)\]")
    for line in hlo.splitlines():
        if "-done" in line:  # paired with the -start op; count once
            continue
        m = pat.search(line)
        if not m or "=" not in line:
            continue
        kind = m.group(1)
        # the result shape follows '=': `%x = bf16[16,4096]{...} all-reduce(...)`
        # (tuple-shaped async starts list several arrays; sum them all)
        rhs = line.split("=", 1)[1]
        rhs = rhs.split(kind)[0]  # shapes before the op name = result shapes
        nbytes = 0
        for sm in shape_pat.finditer(rhs):
            dt, dims = sm.group(1), sm.group(2)
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * dtype_bytes.get(dt, 4)
        inv[kind]["count"] += 1
        inv[kind]["bytes_moved"] += nbytes
    return dict(inv)


def lower_target(config_path: str, topology: str, hbm_key: str = "v5p",
                 overrides: typing.Optional[dict] = None,
                 keep_hlo_lines: int = 0) -> dict:
    """AOT-compile ``config_path``'s training step for ``topology``; return
    the memory/collective report (raises if compilation itself fails)."""
    import numpy as np
    import jax
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.core import sharding as shardlib
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer, TrainState

    t0 = time.monotonic()
    td = topologies.get_topology_desc(platform="tpu", topology_name=topology)
    devices = td.devices
    if not os.path.isabs(config_path) and not os.path.exists(config_path):
        config_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "..", config_path)
    cfg = json.load(open(config_path))
    cfg.update(overrides or {})
    cfg["model_path"] = "/tmp/pod_lowering"
    params = ModelParameter(cfg)

    mesh = shardlib.build_mesh(params, devices)
    model = Model(params)
    trainer = Trainer(params, model, mesh)

    # memory-aware kernel/stash heuristics must budget against the TARGET
    # chips, not the local client (a CPU/tunnel process lowering for a v5p
    # pod would otherwise bake a 16GiB-derived dq-partial cap into a 95GiB
    # chip's executable).  resolve_stash reads the mesh's own devices; the
    # fused-backward cap has no device argument, so pin it via its env
    # override for the lowering
    from homebrewnlp_tpu.utils.flops import device_hbm_bytes
    target_hbm = device_hbm_bytes(devices[0])
    cap_key = "HBNLP_FUSED_DQP_CAP_GB"

    def _lower_with_cap():
        seq = params.sequence_length // params.token_patch_size
        batch_np = {
            "token_x": np.zeros((params.train_batch_size, seq,
                                 params.token_patch_size), np.int32),
            "token_y": np.zeros((params.train_batch_size, seq,
                                 params.token_patch_size), np.int32)}

        undo = _patch_cheap_init()
        try:
            variables = model.init(batch_np)
        finally:
            undo()
        trainer.optimizer = __import__(
            "homebrewnlp_tpu.optim", fromlist=["Optimizer"]).Optimizer(
                params, model.param_dims)

        var_avals = {
            k: jax.ShapeDtypeStruct(
                np.shape(v), np.asarray(v).dtype,
                sharding=shardlib.named_sharding(
                    params, model.param_dims.get(k, ()), mesh))
            for k, v in variables.items()}
        n_params = sum(int(np.prod(a.shape)) for a in var_avals.values())
        del variables  # free the host zeros before compiling

        opt_avals = _opt_state_avals(trainer.optimizer, var_avals, mesh)
        repl = NamedSharding(mesh, PartitionSpec())
        state_avals = TrainState(
            var_avals, opt_avals,
            jax.ShapeDtypeStruct((), np.int32, sharding=repl))

        batch_entries = [None] * 3
        if params.train_batch_size % mesh.shape.get("data", 1) == 0:
            batch_entries[0] = "data"
        batch_sharding = NamedSharding(mesh, PartitionSpec(*batch_entries))
        batch_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype,
                                               sharding=batch_sharding)
                       for k, v in batch_np.items()}
        rng_aval = jax.ShapeDtypeStruct((2,), np.uint32, sharding=repl)

        step_fn = trainer._build_step()
        t_trace = time.monotonic()
        lowered = step_fn.lower(state_avals, batch_avals, rng_aval)
        t_lower = time.monotonic()
        compiled = lowered.compile()
        t_compile = time.monotonic()

        ma = compiled.memory_analysis()
        hlo = compiled.as_text()
        inventory = _collective_inventory(hlo)

        hbm = HBM_BYTES[hbm_key]
        # donated state aliases the output, so peak live ≈ arguments (params +
        # opt state + batch) + XLA temporaries (activations, stash, collective
        # buffers); generated code is tiny by comparison but counted
        peak = (ma.argument_size_in_bytes + ma.temp_size_in_bytes
                + ma.generated_code_size_in_bytes)
        gib = 1024 ** 3
        report = {
            "config": config_path,
            "topology": topology,
            "devices": len(devices),
            "device_kind": str(devices[0].device_kind),
            "mesh": dict(mesh.shape),
            "n_params": n_params,
            "per_chip": {
                "arguments_gib": round(ma.argument_size_in_bytes / gib, 3),
                "output_gib": round(ma.output_size_in_bytes / gib, 3),
                "temp_gib": round(ma.temp_size_in_bytes / gib, 3),
                "alias_gib": round(ma.alias_size_in_bytes / gib, 3),
                "code_gib": round(ma.generated_code_size_in_bytes / gib, 3),
                "peak_estimate_gib": round(peak / gib, 3),
                "hbm_gib": round(hbm / gib, 2),
                "fits": bool(peak < hbm),
            },
            "collectives": inventory,
            "timings_s": {"setup": round(t_trace - t0, 1),
                          "trace_lower": round(t_lower - t_trace, 1),
                          "compile": round(t_compile - t_lower, 1)},
        }
        if keep_hlo_lines:
            report["hlo_head"] = hlo.splitlines()[:keep_hlo_lines]
        return report

    cap_prev = os.environ.get(cap_key)
    os.environ[cap_key] = str(0.30 * target_hbm / 1024 ** 3)
    # the restore spans EVERYTHING from the assignment on (it used to wrap
    # only lower()/compile()): an exception in init/aval construction below
    # would otherwise leak the target-chip cap into the process env,
    # silently mis-budgeting every later lowering in the same process
    try:
        return _lower_with_cap()
    finally:
        if cap_prev is None:
            os.environ.pop(cap_key, None)
        else:
            os.environ[cap_key] = cap_prev


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config")
    ap.add_argument("--topology", default="v5p:4x4x8")
    ap.add_argument("--hbm", default="v5p", choices=sorted(HBM_BYTES))
    ap.add_argument("--override", action="append", default=[],
                    help="config override key=json_value")
    args = ap.parse_args()

    targets = STANDARD_TARGETS
    if args.config:
        overrides = {}
        for ov in args.override:
            k, v = ov.split("=", 1)
            overrides[k] = json.loads(v)
        targets = [(args.config, args.topology, None, args.hbm, overrides)]

    ok = True
    for config, topology, _, hbm_key, overrides in targets:
        report = lower_target(config, topology, hbm_key, overrides)
        print(json.dumps(report), flush=True)
        ok &= report["per_chip"]["fits"]
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
