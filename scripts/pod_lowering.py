#!/usr/bin/env python3
"""AOT pod lowering: compile a config's FULL training step against a detached
TPU topology and report per-chip memory + the collective inventory.

The reference could at least *launch* its flagship on the pod it targeted
(/root/reference/src/main.py:107-147 resolves the real TPU topology before
building the graph); this is the TPU-native, stronger equivalent without pod
hardware: jax AOT compilation against a ``TopologyDescription``
(jax.experimental.topologies) runs the real XLA/Mosaic TPU compiler for the
target chip generation, partitions the step across the full device mesh
(GSPMD + shard_map ring attention), and reports exact per-chip buffer sizes
(``Compiled.memory_analysis()``) plus every cross-chip collective in the
final HLO.  If the config does not fit its pod, this fails loudly — without
burning a pod-hour.

Usage:
  python scripts/pod_lowering.py                      # both standard targets
  python scripts/pod_lowering.py --config configs/1b_long_context.json \
      --topology v5p:4x4x8 [--hbm-gb 95]

Prints one JSON report per target; non-zero exit if any target exceeds HBM.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import typing

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

# v5p HBM per chip (95 GiB usable of 96); v5e is 16
HBM_BYTES = {"v5p": 95 * 1024 ** 3, "v5e": 15.75 * 1024 ** 3}

STANDARD_TARGETS = [
    # (config, topology, expected devices, HBM key) — the 1B long-context
    # target at its configured tpu_size 128 (BASELINE.json configs[4]) and
    # the flagship at tpu_size 64 (VERDICT r4 next-round #1)
    ("configs/1b_long_context.json", "v5p:4x4x8", 128, "v5p", {}),
    ("configs/32big_mixer.json", "v5p:4x4x4", 64, "v5p", {"tpu_size": 64}),
]


def _collective_inventory(hlo: str, mesh_shape=None) -> typing.Dict[str, dict]:
    """Thin shim onto the ONE shared census (analysis/hlo_lint.py
    ``collective_inventory``): async start/done pairs counted once, the
    same spelling fallbacks, result-bytes accounting — the dryrun report
    and the lint layer can no longer disagree on a count.  ``mesh_shape``
    adds per-mesh-axis attribution to each kind."""
    from homebrewnlp_tpu.analysis import hlo_lint
    return hlo_lint.collective_inventory(hlo, mesh_shape)


def lower_target(config_path: str, topology: str, hbm_key: str = "v5p",
                 overrides: typing.Optional[dict] = None,
                 keep_hlo_lines: int = 0) -> dict:
    """AOT-compile ``config_path``'s training step for ``topology``; return
    the memory/collective report (raises if compilation itself fails)."""
    from jax.experimental import topologies

    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.core import sharding as shardlib
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer

    t0 = time.monotonic()
    td = topologies.get_topology_desc(platform="tpu", topology_name=topology)
    devices = td.devices
    if not os.path.isabs(config_path) and not os.path.exists(config_path):
        config_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "..", config_path)
    cfg = json.load(open(config_path))
    cfg.update(overrides or {})
    cfg["model_path"] = "/tmp/pod_lowering"
    params = ModelParameter(cfg)

    mesh = shardlib.build_mesh(params, devices)
    model = Model(params)
    trainer = Trainer(params, model, mesh)

    # memory-aware kernel/stash heuristics must budget against the TARGET
    # chips, not the local client (a CPU/tunnel process lowering for a v5p
    # pod would otherwise bake a 16GiB-derived dq-partial cap into a 95GiB
    # chip's executable).  resolve_stash reads the mesh's own devices; the
    # fused-backward cap has no device argument, so pin it via its env
    # override for the lowering
    from homebrewnlp_tpu.utils.flops import device_hbm_bytes
    target_hbm = device_hbm_bytes(devices[0])
    cap_key = "HBNLP_FUSED_DQP_CAP_GB"

    def _lower_with_cap():
        # ONE aval-construction + lowering path shared with the mesh audit
        # (analysis/mesh_audit.py train_step_avals): cheap zero-init for the
        # QR matrices, layout-derived NamedShardings for params, the REAL
        # Optimizer.init slot discovery for opt-state avals, batch over
        # 'data' where divisible
        from homebrewnlp_tpu.analysis import mesh_audit

        state_avals, batch_avals, rng_aval, info = mesh_audit.train_step_avals(
            params, model, mesh, cheap_init=True)
        n_params = info["n_params"]
        trainer.optimizer = info["optimizer"]

        step_fn = trainer._build_step()
        t_trace = time.monotonic()
        lowered = step_fn.lower(state_avals, batch_avals, rng_aval)
        t_lower = time.monotonic()
        compiled = lowered.compile()
        t_compile = time.monotonic()

        ma = compiled.memory_analysis()
        hlo = compiled.as_text()
        inventory = _collective_inventory(hlo, dict(mesh.shape))

        hbm = HBM_BYTES[hbm_key]
        # donated state aliases the output, so peak live ≈ arguments (params +
        # opt state + batch) + XLA temporaries (activations, stash, collective
        # buffers); generated code is tiny by comparison but counted
        peak = (ma.argument_size_in_bytes + ma.temp_size_in_bytes
                + ma.generated_code_size_in_bytes)
        gib = 1024 ** 3
        report = {
            "config": config_path,
            "topology": topology,
            "devices": len(devices),
            "device_kind": str(devices[0].device_kind),
            "mesh": dict(mesh.shape),
            "n_params": n_params,
            "per_chip": {
                "arguments_gib": round(ma.argument_size_in_bytes / gib, 3),
                "output_gib": round(ma.output_size_in_bytes / gib, 3),
                "temp_gib": round(ma.temp_size_in_bytes / gib, 3),
                "alias_gib": round(ma.alias_size_in_bytes / gib, 3),
                "code_gib": round(ma.generated_code_size_in_bytes / gib, 3),
                "peak_estimate_gib": round(peak / gib, 3),
                "hbm_gib": round(hbm / gib, 2),
                "fits": bool(peak < hbm),
            },
            "collectives": inventory,
            "timings_s": {"setup": round(t_trace - t0, 1),
                          "trace_lower": round(t_lower - t_trace, 1),
                          "compile": round(t_compile - t_lower, 1)},
        }
        if keep_hlo_lines:
            report["hlo_head"] = hlo.splitlines()[:keep_hlo_lines]
        return report

    cap_prev = os.environ.get(cap_key)
    os.environ[cap_key] = str(0.30 * target_hbm / 1024 ** 3)
    # the restore spans EVERYTHING from the assignment on (it used to wrap
    # only lower()/compile()): an exception in init/aval construction below
    # would otherwise leak the target-chip cap into the process env,
    # silently mis-budgeting every later lowering in the same process
    try:
        return _lower_with_cap()
    finally:
        if cap_prev is None:
            os.environ.pop(cap_key, None)
        else:
            os.environ[cap_key] = cap_prev


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config")
    ap.add_argument("--topology", default="v5p:4x4x8")
    ap.add_argument("--hbm", default="v5p", choices=sorted(HBM_BYTES))
    ap.add_argument("--override", action="append", default=[],
                    help="config override key=json_value")
    args = ap.parse_args()

    targets = STANDARD_TARGETS
    if args.config:
        overrides = {}
        for ov in args.override:
            k, v = ov.split("=", 1)
            overrides[k] = json.loads(v)
        targets = [(args.config, args.topology, None, args.hbm, overrides)]

    ok = True
    for config, topology, _, hbm_key, overrides in targets:
        report = lower_target(config, topology, hbm_key, overrides)
        print(json.dumps(report), flush=True)
        ok &= report["per_chip"]["fits"]
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
