#!/usr/bin/env python3
"""Text -> TFRecord dataset builder.

Equivalent of the reference's data-prep pipeline
(/root/reference/scripts/text2tfrecord.py and the Cython
local_text2tfrecord.pyx): chunks input text files into TFRecords holding a
single 'text' feature (raw bytes, or int64 token ids with --tokens), named
``<prefix>_<index>_<tokencount>.tfrecord`` so the deterministic-resume
simulation (homebrewnlp_tpu/data/inputs.py) can replay consumption from the
filename convention.

Inputs may be plain text, ``.jsonl`` (one {"text": ...} object per line),
or Pile-style ``.jsonl.zst`` / ``.zst`` shards (the reference streamed The
Pile's 30 zstd shards, text2tfrecord.py:35-107; this reads the same format
from local files — zero-egress image).  Optional ``--gpt2-bpe`` encodes
with a tokenizer.json (e.g. from scripts/train_tokenizer.py) into int64
records instead of raw bytes.
"""
import argparse
import io
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example  # noqa: E402


def _iter_text(path: str, chunk_bytes: int, text_mode: bool = False):
    """Yield byte chunks from txt / jsonl / zstd-compressed jsonl files.

    ``text_mode``: decode plain files through a text stream (incremental
    UTF-8 decoding, so multi-byte chars never split at chunk boundaries) —
    required when the chunks feed a tokenizer; raw-bytes datasets keep the
    exact file bytes."""
    if path.endswith(".zst"):
        import zstandard
        with open(path, "rb") as raw:
            stream = zstandard.ZstdDecompressor(max_window_size=2 ** 31)\
                .stream_reader(raw)
            if ".jsonl" in path or _peek_jsonl(path):
                yield from _iter_jsonl_lines(
                    io.TextIOWrapper(stream, errors="ignore"), chunk_bytes)
            elif text_mode:
                text = io.TextIOWrapper(stream, errors="ignore")
                while True:
                    chunk = text.read(chunk_bytes)
                    if not chunk:
                        return
                    yield chunk.encode()
            else:
                # raw-bytes mode: exact decompressed bytes, no re-decode
                while True:
                    chunk = stream.read(chunk_bytes)
                    if not chunk:
                        return
                    yield chunk
    elif path.endswith(".jsonl"):
        with open(path, errors="ignore") as f:
            yield from _iter_jsonl_lines(f, chunk_bytes)
    elif text_mode:
        with open(path, errors="ignore") as f:
            while True:
                chunk = f.read(chunk_bytes)
                if not chunk:
                    return
                yield chunk.encode()
    else:
        with open(path, "rb") as f:
            while True:
                chunk = f.read(chunk_bytes)
                if not chunk:
                    return
                yield chunk


def _peek_jsonl(path: str) -> bool:
    """Pile shards are .jsonl.zst but sometimes named .zst only: treat as
    jsonl if the first line parses to an object with a 'text' field, or is a
    json-object prefix too long to finish within the peek window (huge first
    documents are still json, never plain text starting with '{\"text\"')."""
    import zstandard
    limit = 8 << 20
    with open(path, "rb") as raw:
        stream = zstandard.ZstdDecompressor(max_window_size=2 ** 31)\
            .stream_reader(raw)
        head = io.TextIOWrapper(stream, errors="ignore").readline(limit)
    try:
        doc = json.loads(head)
        return isinstance(doc, dict) and "text" in doc
    except json.JSONDecodeError:
        return (len(head) >= limit and "\n" not in head
                and head.lstrip()[:1] == "{")


def _iter_jsonl_lines(f, chunk_bytes: int):
    # every document ends with "\n" so records never fuse across chunks
    buf, size = [], 0
    for line in f:
        try:
            doc = json.loads(line)
            text = doc.get("text") if isinstance(doc, dict) else None
        except json.JSONDecodeError:
            continue
        if not isinstance(text, str) or not text:
            continue
        buf.append(text)
        size += len(text)
        if size >= chunk_bytes:
            yield ("\n".join(buf) + "\n").encode()
            buf, size = [], 0
    if buf:
        yield ("\n".join(buf) + "\n").encode()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("inputs", nargs="+",
                    help="input text / jsonl / jsonl.zst files")
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--prefix", default="part")
    ap.add_argument("--chunk-tokens", type=int, default=2 ** 20,
                    help="tokens per output file")
    ap.add_argument("--records-per-file", type=int, default=1)
    ap.add_argument("--tokens", action="store_true",
                    help="treat input as whitespace-separated int token ids "
                         "(writes int64 features, filenames tagged 'int64')")
    ap.add_argument("--gpt2-bpe", metavar="TOKENIZER_JSON", default=None,
                    help="encode text with this tokenizer.json into int64 "
                         "records (reference text2tfrecord.py BPE mode)")
    args = ap.parse_args()

    encoder = None
    if args.gpt2_bpe:
        from tokenizers import Tokenizer
        encoder = Tokenizer.from_file(args.gpt2_bpe)

    os.makedirs(args.output_dir, exist_ok=True)
    file_idx = 0
    buffer: list = []

    def flush():
        nonlocal file_idx, buffer
        if not buffer:
            return
        total = sum(len(b) for b in buffer)
        tag = "int64_" if (args.tokens or encoder) else ""
        name = f"{args.prefix}_{tag}{file_idx:05d}_{total}.tfrecord"
        with RecordWriter(os.path.join(args.output_dir, name)) as w:
            per_record = max(1, len(buffer) // args.records_per_file)
            for i in range(0, len(buffer), per_record):
                group = buffer[i:i + per_record]
                if args.tokens or encoder:
                    ids = [t for chunk in group for t in chunk]
                    w.write(encode_example({"text": ids}))
                else:
                    w.write(encode_example({"text": b"".join(group)}))
        print(f"wrote {name} ({total} tokens)")
        file_idx += 1
        buffer = []

    pending = 0
    for path in args.inputs:
        if args.tokens:
            with open(path) as f:
                ids = [int(t) for t in f.read().split()]
            step = args.chunk_tokens
            for i in range(0, len(ids), step):
                buffer.append(ids[i:i + step])
                pending += len(buffer[-1])
                if pending >= args.chunk_tokens:
                    flush()
                    pending = 0
        else:
            n_chunks = 0
            for chunk in _iter_text(path, args.chunk_tokens,
                                    text_mode=encoder is not None):
                n_chunks += 1
                if encoder is not None:
                    chunk = encoder.encode(
                        chunk.decode(errors="ignore")).ids
                buffer.append(chunk)
                pending += len(chunk)
                if pending >= args.chunk_tokens:
                    flush()
                    pending = 0
            if n_chunks == 0:
                print(f"WARNING: {path} yielded no text — if it is not "
                      f"jsonl, the jsonl sniffing may have misrouted it",
                      file=sys.stderr)
    flush()


if __name__ == "__main__":
    main()
