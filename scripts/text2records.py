#!/usr/bin/env python3
"""Text -> TFRecord dataset builder.

Equivalent of the reference's data-prep pipeline
(/root/reference/scripts/text2tfrecord.py and the Cython
local_text2tfrecord.pyx): chunks input text files into TFRecords holding a
single 'text' feature (raw bytes, or int64 token ids with --tokens), named
``<prefix>_<index>_<tokencount>.tfrecord`` so the deterministic-resume
simulation (homebrewnlp_tpu/data/inputs.py) can replay consumption from the
filename convention.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("inputs", nargs="+", help="input text files")
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--prefix", default="part")
    ap.add_argument("--chunk-tokens", type=int, default=2 ** 20,
                    help="tokens per output file")
    ap.add_argument("--records-per-file", type=int, default=1)
    ap.add_argument("--tokens", action="store_true",
                    help="treat input as whitespace-separated int token ids "
                         "(writes int64 features, filenames tagged 'int64')")
    args = ap.parse_args()

    os.makedirs(args.output_dir, exist_ok=True)
    file_idx = 0
    buffer: list = []

    def flush():
        nonlocal file_idx, buffer
        if not buffer:
            return
        total = sum(len(b) for b in buffer)
        tag = "int64_" if args.tokens else ""
        name = f"{args.prefix}_{tag}{file_idx:05d}_{total}.tfrecord"
        with RecordWriter(os.path.join(args.output_dir, name)) as w:
            per_record = max(1, len(buffer) // args.records_per_file)
            for i in range(0, len(buffer), per_record):
                group = buffer[i:i + per_record]
                if args.tokens:
                    ids = [t for chunk in group for t in chunk]
                    w.write(encode_example({"text": ids}))
                else:
                    w.write(encode_example({"text": b"".join(group)}))
        print(f"wrote {name} ({total} tokens)")
        file_idx += 1
        buffer = []

    pending = 0
    for path in args.inputs:
        if args.tokens:
            with open(path) as f:
                ids = [int(t) for t in f.read().split()]
            step = args.chunk_tokens
            for i in range(0, len(ids), step):
                buffer.append(ids[i:i + step])
                pending += len(buffer[-1])
                if pending >= args.chunk_tokens:
                    flush()
                    pending = 0
        else:
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(args.chunk_tokens)
                    if not chunk:
                        break
                    buffer.append(chunk)
                    pending += len(chunk)
                    if pending >= args.chunk_tokens:
                        flush()
                        pending = 0
    flush()


if __name__ == "__main__":
    main()
