#!/usr/bin/env python3
"""Host input-pipeline throughput: can one host process feed a pod's chips?

VERDICT r4 missing #3: the training numbers prove the loader keeps up with
ONE chip implicitly; nothing showed it against per-host pod demand.  The
reference engineered its tf.data pipeline for exactly this surface
(/root/reference/src/run/dataloader_placement.py:153-176 — per-host infeed
with tuned thread/buffer options).  This benchmark measures the rebuilt
pipeline standalone — TextDataset window assembly over TFRecord shards +
the background-thread Prefetcher, exactly the objects the train loop
consumes — in tokens/sec per host process, across:

- the C++ record scanner (native/recordio.cpp) vs the pure-python framing
- interleave widths (``interleaved_datasets``)
- the two bench shapes: flagship (batch 32 x seq 512) and long-context
  (batch 1 x seq 16384)

Demand reference points (v5e-8, one host, 8 chips): flagship 8 x 26.4k =
211k tok/s; 16k-context 8 x 47.7k = 381k tok/s.  PASS = sustained loader
rate >= 2x demand (leaves headroom for jitter + the train loop's own host
work).

Usage: python scripts/bench_loader.py [--glob data/loaderbench/*] [--seconds 8]
Prints one JSON line per variant + a summary line.

Corpus (data/ is a gitignored scratch dir — build once):
  python scripts/text2records.py corpus.txt --output-dir data/loaderbench \
      --prefix lb --chunk-tokens $((8*1024*1024))
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

DEMAND_PER_CHIP = {"flagship": 26_436, "long16k": 47_656}
CHIPS_PER_HOST = 8


def measure(glob_pattern: str, batch: int, seq: int, interleave: int,
            native: bool, seconds: float, prefetch: bool = True) -> dict:
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.data import native_recordio
    from homebrewnlp_tpu.data.inputs import Prefetcher, TextDataset

    saved = native_recordio.available
    if native and not native_recordio.available():
        # without this, the python framing would be measured under a
        # native_scanner=true label (tfrecord.read_records falls back
        # silently) and the C++-vs-python comparison would be meaningless
        raise RuntimeError("C++ record scanner requested but not built "
                           "(native/recordio.cpp)")
    if not native:
        native_recordio.available = lambda: False
    try:
        params = ModelParameter({
            "model_mode": "gpt", "use_video": False, "use_language": True,
            "sequence_length": seq, "train_batch_size": batch,
            "features_per_head": 16, "heads": 2, "depth": 2,
            "vocab_size": 256, "interleaved_datasets": interleave,
            "dataset_configs": [{"path": glob_pattern, "type": "text",
                                 "weight": 1}],
            "model_path": "/tmp/bench_loader"})
        ds = TextDataset(params, batch)
        it = iter(Prefetcher(iter(ds), depth=2) if prefetch else iter(ds))
        # warm: first batch pays file-open + (python path) full-file read
        next(it)
        t0 = time.monotonic()
        batches = 0
        while time.monotonic() - t0 < seconds:
            next(it)
            batches += 1
        dt = time.monotonic() - t0
        if prefetch:
            it.close()
        tokens = batches * batch * seq
        return {"batch": batch, "seq": seq, "interleave": interleave,
                "native_scanner": native, "prefetch": prefetch,
                "tokens_per_sec": round(tokens / dt, 1),
                "batches_per_sec": round(batches / dt, 2)}
    finally:
        native_recordio.available = saved


def _measure_subprocess(glob_pattern, batch, seq, interleave, native,
                        seconds, prefetch=True) -> dict:
    """One variant per fresh interpreter: each measurement leaves behind a
    live Prefetcher daemon thread (blocked on its full queue but holding
    open file generators); accumulated across variants in one process they
    skew later numbers badly (measured: the last variant read 3 orders of
    magnitude slow in-sequence, full speed isolated)."""
    import subprocess
    code = (
        "import json, sys; sys.path.insert(0, %r); import bench_loader as b;"
        "print(json.dumps(b.measure(%r, %d, %d, %d, %r, %r, %r)))"
        % (os.path.dirname(os.path.abspath(__file__)), glob_pattern, batch,
           seq, interleave, native, seconds, prefetch))
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=seconds * 10 + 240)
    for line in proc.stdout.splitlines():
        try:
            return json.loads(line)
        except ValueError:
            continue
    return {"error": f"rc={proc.returncode}",
            "stderr_tail": (proc.stderr or "").strip().splitlines()[-3:]}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--glob", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..",
        "data/loaderbench/*"))
    ap.add_argument("--seconds", type=float, default=8.0)
    args = ap.parse_args()

    shapes = {"flagship": (32, 512), "long16k": (1, 16384)}
    results = []
    for shape, (batch, seq) in shapes.items():
        for native in (True, False):
            for interleave in (1, 4, 16):
                r = _measure_subprocess(args.glob, batch, seq, interleave,
                                        native, args.seconds)
                r["shape"] = shape
                if "tokens_per_sec" in r:
                    demand = DEMAND_PER_CHIP[shape] * CHIPS_PER_HOST
                    r["pod_host_demand"] = demand
                    r["x_demand"] = round(r["tokens_per_sec"] / demand, 2)
                    results.append(r)
                print(json.dumps(r), flush=True)
    if not results:
        print(json.dumps({"error": "no variant succeeded — build the "
                                   "corpus first (see module docstring)"}))
        return 1
    # no-prefetch probe at the best config of each shape: isolates the
    # prefetch thread's contribution
    for shape, (batch, seq) in shapes.items():
        per_shape = [r for r in results if r["shape"] == shape]
        if not per_shape:
            continue
        best = max(per_shape, key=lambda r: r["tokens_per_sec"])
        r = _measure_subprocess(args.glob, batch, seq, best["interleave"],
                                best["native_scanner"], args.seconds,
                                prefetch=False)
        r["shape"] = shape + "/no-prefetch"
        print(json.dumps(r), flush=True)
    summary = {}
    for shape in shapes:
        per_shape = [r for r in results if r["shape"] == shape]
        if not per_shape:
            continue
        best = max(per_shape, key=lambda r: r["tokens_per_sec"])
        summary[shape] = {"best_tokens_per_sec": best["tokens_per_sec"],
                          "x_pod_host_demand": best["x_demand"],
                          "config": {k: best[k] for k in
                                     ("interleave", "native_scanner")}}
    print(json.dumps({"summary": summary}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
