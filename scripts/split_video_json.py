#!/usr/bin/env python3
"""Balanced splitting of video id/duration work lists across workers.

Equivalent of /root/reference/scripts/split_video_json.py +
chunk_video_json.py: greedy longest-first bin packing of
``[{"id": ..., "duration": ...}, ...]`` into N near-equal-duration chunks.
"""
import argparse
import json
import os


def balanced_split(items, n):
    bins = [[] for _ in range(n)]
    totals = [0.0] * n
    for item in sorted(items, key=lambda x: -float(x.get("duration", 1))):
        i = totals.index(min(totals))
        bins[i].append(item)
        totals[i] += float(item.get("duration", 1))
    return bins, totals


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("input", help="JSON list of {id, duration} entries")
    ap.add_argument("--splits", type=int, required=True)
    ap.add_argument("--output-dir", required=True)
    args = ap.parse_args()

    with open(args.input) as f:
        items = json.load(f)
    if isinstance(items, dict):
        items = [{"id": k, "duration": v} for k, v in items.items()]

    bins, totals = balanced_split(items, args.splits)
    os.makedirs(args.output_dir, exist_ok=True)
    base = os.path.splitext(os.path.basename(args.input))[0]
    for i, (chunk, total) in enumerate(zip(bins, totals)):
        path = os.path.join(args.output_dir, f"{base}_{i:03d}.json")
        with open(path, "w") as w:
            json.dump(chunk, w)
        print(f"{path}: {len(chunk)} videos, {total:.0f}s")


if __name__ == "__main__":
    main()
