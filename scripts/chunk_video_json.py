#!/usr/bin/env python3
"""Chunk video id/duration lists into work units of at least a minimum total
duration (reference: /root/reference/scripts/chunk_video_json.py:1-86 —
sibling of split_video_json.py, which balance-splits across a fixed worker
count; this one greedily packs shuffled videos until each chunk reaches
``min_duration`` seconds).

Input json: {"id": [...], "duration": [...]} — one file or a directory of
them.  Output: {prefix}work_chunks.json with {"id": [[...], ...],
"duration": [[...], ...]}.
"""
import argparse
import json
import os
import random


def chunk(ids, durations, min_duration, seed=None):
    videos = list(zip(ids, durations))
    rng = random.Random(seed)
    rng.shuffle(videos)
    chunks_ids, chunks_dur = [], []
    cur_ids, cur_dur, cur_sum = [], [], 0
    for vid, dur in videos:
        cur_ids.append(vid)
        cur_dur.append(dur)
        cur_sum += dur
        if cur_sum >= min_duration:
            chunks_ids.append(cur_ids)
            chunks_dur.append(cur_dur)
            cur_ids, cur_dur, cur_sum = [], [], 0
    if cur_ids:
        chunks_ids.append(cur_ids)
        chunks_dur.append(cur_dur)
    return chunks_ids, chunks_dur


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("load_path",
                    help="json file with video info, or a directory of them")
    ap.add_argument("min_duration", type=int,
                    help="minimum total seconds per chunk")
    ap.add_argument("-prefix", type=str, default="", help="save-file prefix")
    ap.add_argument("-seed", type=int, default=None,
                    help="shuffle seed (reference shuffles unseeded)")
    args = ap.parse_args()

    paths = ([os.path.join(args.load_path, p)
              for p in sorted(os.listdir(args.load_path))]
             if os.path.isdir(args.load_path) else [args.load_path])
    ids, durations = [], []
    for p in paths:
        with open(p) as f:
            data = json.load(f)
        ids += data["id"]
        durations += data["duration"]

    chunks_ids, chunks_dur = chunk(ids, durations, args.min_duration,
                                   args.seed)
    total_videos = total_dur = 0
    for i, (ci, cd) in enumerate(zip(chunks_ids, chunks_dur)):
        print(f"chunk: {i} videos: {len(ci)} duration: {sum(cd)}")
        total_videos += len(ci)
        total_dur += sum(cd)
    print(f"\ntotal num of videos: {total_videos} "
          f"total video duration: {total_dur}")

    out = f"{args.prefix}work_chunks.json"
    with open(out, "w") as f:
        json.dump({"id": chunks_ids, "duration": chunks_dur}, f)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
