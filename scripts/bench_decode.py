"""Decode throughput bench: KV-cached sampling at the flagship recipe.

Generates a full sequence with the cached sampler (infer/sampler.py) at the
given batch sizes and reports ms/token and aggregate tokens/sec as JSON
lines.  Run on the TPU chip:

  nohup python scripts/bench_decode.py --batches 1,8,32 > decode_bench.log &

Timing notes (docs/PERFORMANCE.md): the flagship numbers run the whole
generation inside ONE jitted while_loop call, so per-dispatch tunnel latency
amortises; sync is by value materialisation.  ``--probe`` (and ``run()``,
the bench.py companion) instead measures the big-cache sequence-scaling
probe through the STEPPED donated-carry loop — ms/token at 8k/16k/32k for
bf16 and int8 caches, the tracked regression metric for the in-place
cache-carry property (docs/PERFORMANCE.md 'Big-cache decode').
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# sequence-scaling probe recipe (BASELINE.md round 5): a quarter-width
# 1b_long_context-style mixer — decode cost should be LINEAR in cache bytes
# (one cache read per token), so ms/token at 8k must be ~1/4 of 32k; the
# fused-loop regression showed 6x for the 4x cache (cache-carry copies)
PROBE_CONFIG = {
    "model_mode": "gpt", "use_video": False, "use_language": True,
    "features_per_head": 256, "heads": 16, "depth": 13,
    "train_batch_size": 1, "vocab_size": 256, "calc_accuracy": False,
    "memory_reduction_strategy": "revnet",
    "block_config": [
        {"layer": ["norm-shift-scale-features-group",
                   "bottleneck_group_linear-in:relu-mid:relu-mid:norm-mid:shift-mid:scale-mid:features"]},
        {"layer": ["norm-shift-scale-features-group",
                   "attention-dot_product-context-in:relu"]}],
    "group_linear_factor": 2,
    "intermediate_feed_forward_multiplier_multiplier": 0.5,
    "calculation_dtype": "bfloat16", "storage_dtype": "bfloat16",
    "scan_layers": True, "use_checkpointing": False,
    "model_path": "/tmp/bench_decode_probe",
}


def _measure_stepped(model, variables, token_x, gen: int) -> dict:
    """Steady-state decode ms/token at a FULL cache: prefill to
    ``seq - gen - 1`` in its own jitted call (timed separately as TTFT),
    then time the donated chunk loop over the last ``gen`` tokens —
    prefill cost and compile are excluded from the per-token figure."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from homebrewnlp_tpu.infer.sampler import _jit_sampler

    batch, seq = token_x.shape[0], token_x.shape[1]
    n0 = seq - gen - 1
    ipb = jnp.full((batch,), n0 + 1, jnp.int32)
    tb = jnp.zeros((batch,), jnp.float32)
    prep = _jit_sampler(model, None, "kv_prep")
    token_x, _ = prep(jnp.asarray(token_x), ipb)
    pf = _jit_sampler(model, None, "kv_prefill_caches")
    t0 = time.monotonic()
    caches = pf(variables, token_x, jnp.asarray(n0, jnp.int32))
    # sync by value materialisation (the tunnel's block_until_ready can
    # return early); one scalar read forces the dispatched chain
    np.asarray(jax.tree_util.tree_leaves(caches)[0].ravel()[:1])
    ttft = time.monotonic() - t0

    step = _jit_sampler(model, None, "kv_step")
    chunk = max(1, int(model.params.decode_chunk_tokens))
    end = jnp.asarray(seq, jnp.int32)
    carry = (jnp.asarray(n0, jnp.int32), token_x, caches,
             jax.random.PRNGKey(0))
    # a SHORT warmup chunk compiles the step; timing starts after it so
    # most of ``gen`` lands in the timed window.  min(4, gen - 1) always
    # leaves >= 1 timed step — a zero-step window would silently report
    # ~0 ms/token for the tracked metric
    # (at gen == 1 the warmup call is a no-op that still compiles)
    warm = n0 + min(4, max(seq - 1 - n0 - 1, 0))
    carry = step(variables, ipb, tb, end, jnp.asarray(warm, jnp.int32),
                 (), carry)
    q = int(carry[0])
    t0 = time.monotonic()
    while q < seq - 1:
        q_hi = min(q + chunk, seq - 1)
        carry = step(variables, ipb, tb, end,
                     jnp.asarray(q_hi, jnp.int32), (), carry)
        q = q_hi
    np.asarray(carry[0])  # value sync
    dt = time.monotonic() - t0
    timed = (seq - 1) - warm
    if timed < 1:
        raise ValueError(f"gen={gen} leaves no timed decode steps")
    return {"ms_per_token": dt / timed * 1e3,
            "prefill_ttft_s": round(ttft, 3)}


def run(seqs=None, cache_dtypes=("bfloat16", "int8"), gen: int = 128) -> dict:
    """Decode-latency companion (bench.py): ms/token across sequence
    lengths and cache dtypes on the probe recipe, plus the 32k/8k scaling
    ratio the tier-1 regression metric tracks.  Returns the bench.py
    companion dict; ``value`` is the largest-context int8 ms/token."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.infer.sampler import decode_cache_bytes
    from homebrewnlp_tpu.model import Model

    cfg = dict(PROBE_CONFIG)
    on_cpu = jax.default_backend() == "cpu"
    if seqs is None:
        seqs = (512, 1024, 2048) if on_cpu else (8192, 16384, 32768)
    if on_cpu:
        # CPU fallback keeps the STRUCTURE measurable (scaling ratio, loop
        # path) at shapes a CPU can decode in seconds
        cfg.update(features_per_head=32, heads=2, depth=4)
        gen = min(gen, 32)

    rows = []
    by_key = {}
    for cache_dtype in cache_dtypes:
        for seq in seqs:
            try:
                # the WHOLE per-shape body is guarded: a largest-context
                # failure anywhere (init OOM included) keeps the rows the
                # smaller shapes already measured
                c = dict(cfg, sequence_length=int(seq),
                         decode_cache_dtype=cache_dtype)
                params = ModelParameter(c, train=False)
                model = Model(params)
                tps = params.token_patch_size
                x = np.zeros((1, seq // tps, tps), np.int32)
                variables = {k: jnp.asarray(v) for k, v in
                             model.init({"token_x": x,
                                         "token_y": x}).items()}
                rng = np.random.default_rng(0)
                token_x = rng.integers(0, params.vocab_size, x.shape
                                       ).astype(np.int32)
                res = _measure_stepped(model, variables,
                                       jnp.asarray(token_x), gen)
                nbytes = decode_cache_bytes(model, variables, token_x)
            except Exception as exc:
                rows.append({"seq": int(seq), "cache_dtype": cache_dtype,
                             "error": repr(exc)[:200]})
                continue
            row = {"seq": int(seq), "cache_dtype": cache_dtype,
                   "ms_per_token": round(res["ms_per_token"], 3),
                   "prefill_ttft_s": res["prefill_ttft_s"],
                   "cache_gb": round(nbytes / 1024 ** 3, 3)}
            rows.append(row)
            by_key[(cache_dtype, int(seq))] = dict(row, cache_bytes=nbytes)

    out = {"metric": f"decode ms/token @ probe recipe, batch 1, "
                     f"seqs {'/'.join(str(s) for s in seqs)}",
           "unit": "ms/token", "rows": rows}
    big, small = (by_key.get(("int8", seqs[-1])),
                  by_key.get(("int8", seqs[0])))
    if big and small:
        # largest-vs-smallest measured context (8k/32k on TPU; named
        # generically because the CPU fallback runs shrunk seqs and the
        # two must not be read as the same metric)
        out["value"] = big["ms_per_token"]
        out["scaling_ratio_large_small"] = round(
            big["ms_per_token"] / small["ms_per_token"], 3)
        out["byte_ratio_large_small"] = round(
            big["cache_bytes"] / small["cache_bytes"], 3)
    else:
        # fall back to the last SUCCESSFUL row: a trailing per-shape
        # failure (e.g. the largest context OOMing) must not discard the
        # measured rows from the companion line
        ok = [r for r in rows if "ms_per_token" in r]
        if ok:
            out["value"] = ok[-1]["ms_per_token"]
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="configs/32big_mixer.json")
    ap.add_argument("--batches", default="1,8,32")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--cache_dtype", default=None,
                    help="decode_cache_dtype override (bfloat16/int8)")
    ap.add_argument("--ttft", action="store_true",
                    help="time-to-first-token: prompt fills positions "
                         "0..seq-2 (seq-1 tokens), generate ONE token, "
                         "prefill vs per-token walk")
    ap.add_argument("--quantized", action="store_true",
                    help="weight-only int8 (infer/quant.py): halves the "
                         "weight bytes the decode matvecs stream per token")
    ap.add_argument("--probe", action="store_true",
                    help="run the big-cache sequence-scaling probe "
                         "(ms/token at 8k/16k/32k, bf16+int8 caches) "
                         "through the stepped decode loop and exit")
    ap.add_argument("--tpu-recheck", action="store_true", dest="tpu_recheck",
                    help="ROADMAP re-anchor gate: the PR 2 carry fix was "
                         "proven on CPU-backend HLO + scaling probes, but "
                         "the headline 60.1 ms/token 32k decode has NEVER "
                         "been re-measured on silicon (tunnel down since "
                         "round 6).  On a TPU backend this runs the probe "
                         "FIRST and verdicts against the ~16 ms/token "
                         "acceptance; elsewhere it records the blocked "
                         "attempt so the pending re-measure stays loud "
                         "(BASELINE.md)")
    args = ap.parse_args()

    if args.tpu_recheck:
        import jax
        backend = jax.default_backend()
        if backend != "tpu":
            print(json.dumps({
                "tpu_recheck": "blocked", "backend": backend,
                "pending": "32k decode re-measure of the round-5 "
                           "60.1 ms/token row (acceptance <= 16 ms/token "
                           "at 32k int8 through the stepped loop)",
                "action": "re-run `python scripts/bench_decode.py "
                          "--tpu-recheck` the moment a TPU backend is "
                          "live; record the verdict row in BASELINE.md",
            }), flush=True)
            if args.probe:  # a blocked recheck must not swallow --probe
                print(json.dumps(run()), flush=True)
            return
        report = run()
        # run() puts the largest-context int8 ms/token in "value"
        # (32768 on a TPU backend)
        ms32 = report.get("value")
        print(json.dumps({"tpu_recheck": "measured", "backend": backend,
                          "probe": report,
                          "accepts_16ms": bool(ms32 and ms32 <= 16.0)},
                         ), flush=True)
        if args.probe:  # reuse the sweep just measured — never run() twice
            print(json.dumps(report), flush=True)
        return

    if args.probe:
        print(json.dumps(run()), flush=True)
        return

    import jax
    import jax.numpy as jnp
    import numpy as np

    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.infer.sampler import make_kv_sampler
    from homebrewnlp_tpu.model import Model

    with open(args.config) as f:
        cfg = json.load(f)
    cfg.update({"use_checkpointing": False, "dataset_configs": [],
                "model_path": "/tmp/bench_decode"})
    if args.cache_dtype:
        cfg["decode_cache_dtype"] = args.cache_dtype

    for batch in [int(b) for b in args.batches.split(",")]:
        cfg["train_batch_size"] = batch
        params = ModelParameter(dict(cfg), train=False)
        model = Model(params)
        seq = params.sequence_length // params.token_patch_size
        tps = params.token_patch_size
        x = np.zeros((batch, seq, tps), np.int32)
        variables = model.init({"token_x": x, "token_y": x})
        variables = {k: jnp.asarray(v) for k, v in variables.items()}
        if args.quantized:
            from homebrewnlp_tpu.infer.quant import quantize_variables
            variables, scales = quantize_variables(
                variables, model.param_dims,
                getattr(model, "param_fan_in", None))
            model.quant_scales = scales
        token_x = jnp.zeros((batch, seq, tps), jnp.int32)
        if args.ttft:
            # prompt fills all but the last position; end after ONE generated
            # token.  The walk pays one decode step per prompt token before
            # it; prefill pays one full forward.
            prompt = seq - 1
            for kind, prefill in (("walk", False), ("prefill", True)):
                try:
                    fn = jax.jit(make_kv_sampler(model, prefill=prefill))
                    a = (variables, token_x, jnp.int32(prompt),
                         jnp.float32(0.0), jnp.int32(seq),
                         jax.random.PRNGKey(0), None)
                    t_compile = time.monotonic()
                    np.asarray(fn(*a))
                    compile_s = time.monotonic() - t_compile
                    times = []
                    for _ in range(args.repeats):
                        t0 = time.monotonic()
                        np.asarray(fn(*a))
                        times.append(time.monotonic() - t0)
                    print(json.dumps({
                        "batch": batch, "seq": seq, "mode": kind,
                        "prompt": prompt, "compile_s": round(compile_s, 1),
                        "ttft_s": round(min(times), 4)}), flush=True)
                except Exception as e:
                    print(json.dumps({"batch": batch, "mode": kind,
                                      "error": repr(e)[:300]}), flush=True)
            continue
        try:
            # caches=None: zeros built inside the trace — no host-side cache
            # allocation, no unusable-donation double buffer
            fn = jax.jit(make_kv_sampler(model))
            t_compile = time.monotonic()
            out = fn(variables, token_x, jnp.int32(1), jnp.float32(0.8),
                     jnp.int32(seq), jax.random.PRNGKey(0), None)
            np.asarray(out)  # sync by value
            compile_s = time.monotonic() - t_compile
            times = []
            for r in range(args.repeats):
                t0 = time.monotonic()
                out = fn(variables, token_x, jnp.int32(1), jnp.float32(0.8),
                         jnp.int32(seq), jax.random.PRNGKey(r), None)
                np.asarray(out)
                times.append(time.monotonic() - t0)
            best = min(times)
            tokens = (seq - 1) * tps * batch
            print(json.dumps({
                "batch": batch, "seq": seq, "compile_s": round(compile_s, 1),
                "wall_s": round(best, 3),
                "ms_per_token": round(best / ((seq - 1) * tps) * 1e3, 3),
                "tokens_per_sec_aggregate": round(tokens / best, 1)}),
                flush=True)
        except Exception as e:
            print(json.dumps({"batch": batch, "error": repr(e)[:300]}),
                  flush=True)


if __name__ == "__main__":
    main()
