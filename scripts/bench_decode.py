"""Decode throughput bench: KV-cached sampling at the flagship recipe.

Generates a full sequence with the cached sampler (infer/sampler.py) at the
given batch sizes and reports ms/token and aggregate tokens/sec as JSON
lines.  Run on the TPU chip:

  nohup python scripts/bench_decode.py --batches 1,8,32 > decode_bench.log &

Timing notes (docs/PERFORMANCE.md): the whole generation runs inside ONE
jitted while_loop call, so per-dispatch tunnel latency amortises; sync is by
value materialisation.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="configs/32big_mixer.json")
    ap.add_argument("--batches", default="1,8,32")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--cache_dtype", default=None,
                    help="decode_cache_dtype override (bfloat16/int8)")
    ap.add_argument("--ttft", action="store_true",
                    help="time-to-first-token: prompt fills positions "
                         "0..seq-2 (seq-1 tokens), generate ONE token, "
                         "prefill vs per-token walk")
    ap.add_argument("--quantized", action="store_true",
                    help="weight-only int8 (infer/quant.py): halves the "
                         "weight bytes the decode matvecs stream per token")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.infer.sampler import make_kv_sampler
    from homebrewnlp_tpu.model import Model

    with open(args.config) as f:
        cfg = json.load(f)
    cfg.update({"use_checkpointing": False, "dataset_configs": [],
                "model_path": "/tmp/bench_decode"})
    if args.cache_dtype:
        cfg["decode_cache_dtype"] = args.cache_dtype

    for batch in [int(b) for b in args.batches.split(",")]:
        cfg["train_batch_size"] = batch
        params = ModelParameter(dict(cfg), train=False)
        model = Model(params)
        seq = params.sequence_length // params.token_patch_size
        tps = params.token_patch_size
        x = np.zeros((batch, seq, tps), np.int32)
        variables = model.init({"token_x": x, "token_y": x})
        variables = {k: jnp.asarray(v) for k, v in variables.items()}
        if args.quantized:
            from homebrewnlp_tpu.infer.quant import quantize_variables
            variables, scales = quantize_variables(
                variables, model.param_dims,
                getattr(model, "param_fan_in", None))
            model.quant_scales = scales
        token_x = jnp.zeros((batch, seq, tps), jnp.int32)
        if args.ttft:
            # prompt fills all but the last position; end after ONE generated
            # token.  The walk pays one decode step per prompt token before
            # it; prefill pays one full forward.
            prompt = seq - 1
            for kind, prefill in (("walk", False), ("prefill", True)):
                try:
                    fn = jax.jit(make_kv_sampler(model, prefill=prefill))
                    a = (variables, token_x, jnp.int32(prompt),
                         jnp.float32(0.0), jnp.int32(seq),
                         jax.random.PRNGKey(0), None)
                    t_compile = time.time()
                    np.asarray(fn(*a))
                    compile_s = time.time() - t_compile
                    times = []
                    for _ in range(args.repeats):
                        t0 = time.time()
                        np.asarray(fn(*a))
                        times.append(time.time() - t0)
                    print(json.dumps({
                        "batch": batch, "seq": seq, "mode": kind,
                        "prompt": prompt, "compile_s": round(compile_s, 1),
                        "ttft_s": round(min(times), 4)}), flush=True)
                except Exception as e:
                    print(json.dumps({"batch": batch, "mode": kind,
                                      "error": repr(e)[:300]}), flush=True)
            continue
        try:
            # caches=None: zeros built inside the trace — no host-side cache
            # allocation, no unusable-donation double buffer
            fn = jax.jit(make_kv_sampler(model))
            t_compile = time.time()
            out = fn(variables, token_x, jnp.int32(1), jnp.float32(0.8),
                     jnp.int32(seq), jax.random.PRNGKey(0), None)
            np.asarray(out)  # sync by value
            compile_s = time.time() - t_compile
            times = []
            for r in range(args.repeats):
                t0 = time.time()
                out = fn(variables, token_x, jnp.int32(1), jnp.float32(0.8),
                         jnp.int32(seq), jax.random.PRNGKey(r), None)
                np.asarray(out)
                times.append(time.time() - t0)
            best = min(times)
            tokens = (seq - 1) * tps * batch
            print(json.dumps({
                "batch": batch, "seq": seq, "compile_s": round(compile_s, 1),
                "wall_s": round(best, 3),
                "ms_per_token": round(best / ((seq - 1) * tps) * 1e3, 3),
                "tokens_per_sec_aggregate": round(tokens / best, 1)}),
                flush=True)
        except Exception as e:
            print(json.dumps({"batch": batch, "error": repr(e)[:300]}),
                  flush=True)


if __name__ == "__main__":
    main()
