#!/usr/bin/env python3
"""Measured multi-process scaling: tokens/sec/chip at 1→2→4→8 processes.

The dryrun census (scripts/pod_lowering.py, __graft_entry__.dryrun_multichip)
proves every parallel strategy COMPILES and partitions; this actually RUNS
them across real processes and measures the scaling curve — the Mesh-TF
claim (PAPERS.md 1811.02084: one model definition transparently scaled) and
the pjit-TPUv4 measurement template (PAPERS.md 2204.06514) reproduced on the
CPU multiprocess rig.

For each strategy × process count the parent fans out N coordinator-wired
worker processes (JAX_PLATFORMS=cpu, 2 virtual devices each, gloo
collectives via homebrewnlp_tpu.distributed.bootstrap — the same launch path
as scripts/run_manager.py --num-processes).  Every worker runs the REAL
jitted+donated train step over the strategy's mesh; the chief reports
measured tokens/sec, the parent derives per-chip throughput and scaling
efficiency vs the 1-process baseline (weak scaling: the global batch grows
with the data axis, per-chip work constant).

Pipeline-parallel schedules stay a loudly-SKIPPED row: jax 0.4.37's
partial-manual PartitionId gap (analysis/mesh_audit.py classify_env_gap)
breaks their compile regardless of process count; the row records the
reason so a capable environment turns it back into a measurement.

Usage:
  python scripts/bench_multihost.py                     # full sweep
  python scripts/bench_multihost.py --procs 1,2 --strategies dp_tp
  python scripts/bench_multihost.py --out MULTICHIP_MEASURED.json

Writes one JSON report (default MULTICHIP_MEASURED.json at the repo root)
next to the dryrun MULTICHIP rows; nonzero exit when any non-skipped
strategy produced no measurement.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time
import typing

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, ".."))

#: virtual CPU devices per process: 2, so the model/sequence axis exists at
#: EVERY process count (tp/sp inside the process, dp across processes — the
#: realistic pod layout) and the 1-process baseline runs the same program
DEVICES_PER_PROCESS = 2

#: timed steps per measurement (after one executed warmup step)
DEFAULT_STEPS = 8

_SEQ = 64

# axis names inside mesh_shape_override / layout_override dicts are
# config-schema keys (the same spelling every shipped config JSON uses),
# not PartitionSpec literals — outside the mesh-axis-literal rule's scope
STRATEGIES: typing.Dict[str, dict] = {
    # batch over 'data' (cross-process), heads over 'model' (in-process)
    "dp_tp": dict(heads=8),
    # ring-attention sequence parallelism: dot-product attention over a
    # data x sequence mesh
    "ring_sp": dict(
        block_config=[{"layer": ["norm-shift-scale-features-group",
                                 "attention-dot_product-context"]}],
        memory_reduction_strategy="none"),
    # routed top-k MoE, experts sharded over 'model' (dispatch/combine
    # all-to-alls cross the expert axis)
    "moe_ep": dict(
        experts=4, heads=2, features_per_head=32, moe_top_k=2,
        moe_capacity_factor=2.0,
        block_config=[{"layer": [
            "norm-shift-scale-features-group",
            "feed_forward-in:relu-in:mixture_of_experts-in:routed"]}],
        memory_reduction_strategy="none",
        layout_override={"experts": "model", "heads": None}),
    # pipeline parallelism: attempted, expected to classify as an env gap
    # on jax 0.4.37 (partial-manual PartitionId)
    "pp_gpipe": dict(depth=2, heads=8),
}


def _mesh_override(strategy: str, nproc: int) -> dict:
    inner = {"dp_tp": "model", "moe_ep": "model", "ring_sp": "sequence",
             "pp_gpipe": "pipe"}[strategy]
    return {"data": nproc, inner: DEVICES_PER_PROCESS}


def _free_port() -> int:
    from homebrewnlp_tpu.distributed.bootstrap import free_port
    return free_port()


# ---- worker ----------------------------------------------------------------

def collectives_worker(steps: int, sizes_mb: typing.List[float]) -> int:
    """Collectives-only microbenchmark: timed cross-process all-reduces of
    gradient-sized buffers with NO model step, so the scaling curve
    separates gloo/TCP collective cost from core oversubscription (the
    caveat previously folded into one efficiency number).  Each process
    contributes a distinct full-size buffer — a replicated psum would let
    XLA lower a local multiply instead of real communication."""
    from homebrewnlp_tpu.distributed import bootstrap
    bootstrap.maybe_initialize(verbose=False)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from homebrewnlp_tpu.core import sharding as shardlib
    from homebrewnlp_tpu.parallel import compat

    nproc = jax.process_count()
    pid = jax.process_index()
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices).reshape(-1), (shardlib.DATA_AXIS,))
    nshard = len(devices)
    rows = []
    for size_mb in sizes_mb:
        n = max(1, int(size_mb * (1 << 20) // 4))

        def body(x):
            return jax.lax.psum(x[0], shardlib.DATA_AXIS)

        fn = jax.jit(compat.shard_map(
            body, mesh=mesh, in_specs=P(shardlib.DATA_AXIS), out_specs=P(),
            axis_names={shardlib.DATA_AXIS}, check_vma=False))
        x = jax.device_put(
            np.arange(nshard * n, dtype=np.float32).reshape(nshard, n)
            / (nshard * n), NamedSharding(mesh, P(shardlib.DATA_AXIS)))
        jax.block_until_ready(fn(x))  # compile + warm
        t0 = time.monotonic()
        for _ in range(steps):
            out = fn(x)
        jax.block_until_ready(out)
        wall = time.monotonic() - t0
        ms = wall / steps * 1e3
        rows.append({"size_mb": size_mb, "ms_per_allreduce": round(ms, 3),
                     # bus bytes ~ 2x buffer per ring all-reduce; report
                     # the simple buffer-bytes/time rate for comparability
                     "gb_per_sec": round(n * 4 / (ms / 1e3) / 1e9, 3)})
    if pid == 0:
        print("BENCH_MULTIHOST_RESULT " + json.dumps({
            "kind": "collectives", "processes": nproc,
            "devices": len(devices), "steps": steps, "rows": rows}),
            flush=True)
    return 0


def worker(strategy: str, steps: int, batch_per_slice: int,
           grad_allreduce: str = "") -> int:
    from homebrewnlp_tpu.distributed import bootstrap
    multi = bootstrap.maybe_initialize(verbose=False)
    import jax
    import numpy as np

    import __graft_entry__ as graft
    from homebrewnlp_tpu.analysis import mesh_audit
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.core import sharding as shardlib
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer

    nproc = jax.process_count()
    pid = jax.process_index()
    assert multi or nproc == 1
    devices = jax.devices()
    ndev = len(devices)
    overrides = dict(STRATEGIES[strategy])
    if grad_allreduce:
        # the grad-allreduce A/B: both legs run remat_policy=save_dots (the
        # one policy the bucketed partial-manual region supports on this
        # jax), so the ONLY variable between fused and bucketed rows is the
        # collective schedule
        overrides.update(grad_allreduce=grad_allreduce,
                         remat_policy="save_dots")
    global_batch = batch_per_slice * nproc
    params = ModelParameter(graft._config(
        sequence_length=_SEQ, train_batch_size=global_batch,
        tpu_size=ndev, mesh_shape_override=_mesh_override(strategy, nproc),
        **overrides))
    mesh = shardlib.build_mesh(params)
    trainer = Trainer(params, Model(params), mesh=mesh)

    slice_index, slice_count = shardlib.process_data_slice(mesh) \
        if nproc > 1 else (0, 1)
    local = global_batch // slice_count
    rng = np.random.default_rng(1234 + slice_index)
    x = rng.integers(0, params.vocab_size, (local, _SEQ, 1))
    batch = {"token_x": np.asarray(x, np.int32),
             "token_y": np.asarray((x + 1) % params.vocab_size, np.int32)}

    try:
        state = trainer.init_state(batch)
        # warmup: compiles the REAL donated jitted step (the exact program
        # train_loop runs), executes once
        state, metrics = trainer.step(state, batch)
        jax.block_until_ready(metrics["loss"])
    except Exception as exc:  # noqa: BLE001 — classified below
        reason = mesh_audit.classify_env_gap(exc)
        if reason is None:
            raise
        if pid == 0:
            print("BENCH_MULTIHOST_RESULT "
                  + json.dumps({"strategy": strategy, "processes": nproc,
                                "skipped": reason}), flush=True)
        return 0

    t0 = time.monotonic()
    for _ in range(steps):
        state, metrics = trainer.step(state, batch)
    loss = float(np.asarray(jax.device_get(metrics["loss"])))
    wall = time.monotonic() - t0
    tokens = steps * global_batch * _SEQ
    if pid == 0:
        row = {
            "strategy": strategy, "processes": nproc, "devices": ndev,
            "mesh": dict((str(k), int(v)) for k, v in mesh.shape.items()),
            "global_batch": global_batch, "sequence_length": _SEQ,
            "steps": steps, "wall_s": round(wall, 4),
            "loss": round(loss, 4),
            "tokens_per_sec": round(tokens / wall, 1),
            "tokens_per_sec_per_chip": round(tokens / wall / ndev, 1),
        }
        if grad_allreduce:
            row["grad_allreduce"] = grad_allreduce
        print("BENCH_MULTIHOST_RESULT " + json.dumps(row), flush=True)
    return 0


# ---- parent ----------------------------------------------------------------

def _spawn_fleet(strategy: str, nproc: int, steps: int, batch_per_slice: int,
                 timeout: int, retries: int = 1,
                 extra_args: typing.Sequence[str] = ()
                 ) -> typing.Optional[dict]:
    """One fleet, retried once on a nonzero exit: wide fan-outs on a host
    with fewer cores than processes occasionally starve the coordination
    heartbeat (the whole fleet SIGABRTs with 'another task died'), which
    is scheduler pressure, not a property of the strategy under test."""
    from tests.multihost_test import starvation_retry_reason
    for attempt in range(retries + 1):
        row, rcs, outs = _spawn_fleet_once(strategy, nproc, steps,
                                           batch_per_slice, timeout,
                                           extra_args)
        if row is not None:
            return row
        if attempt < retries:
            reason = starvation_retry_reason(rcs, outs)
            print(f"  {strategy} x{nproc}: retrying after fleet failure"
                  + (f" — {reason}" if reason else ""), flush=True)
    return None


def _spawn_fleet_once(strategy: str, nproc: int, steps: int,
                      batch_per_slice: int, timeout: int,
                      extra_args: typing.Sequence[str] = ()
                      ) -> typing.Tuple[typing.Optional[dict],
                                        typing.List[int],
                                        typing.List[str]]:
    """One attempt; returns ``(result_row_or_None, worker_rcs, worker
    outputs)`` so the retry loop can classify the failure shape (the
    shared 1-core gloo-SIGABRT starvation classifier in
    tests/multihost_test.py)."""
    port = _free_port()
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    procs = []
    for pid in range(nproc):
        env = dict(os.environ,
                   HBNLP_COORDINATOR=f"localhost:{port}",
                   HBNLP_NUM_PROCESSES=str(nproc),
                   HBNLP_PROCESS_ID=str(pid),
                   JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
                   XLA_FLAGS=flags + " --xla_force_host_platform_device_"
                   f"count={DEVICES_PER_PROCESS}")
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "--strategies", strategy, "--steps", str(steps),
             "--batch-per-slice", str(batch_per_slice)] + list(extra_args),
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            print(f"  {strategy} x{nproc}: TIMEOUT after {timeout}s",
                  flush=True)
            return None, [p.returncode or -9 for p in procs], outs
        outs.append(out)
    rcs = [p.returncode for p in procs]
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            print(f"  {strategy} x{nproc}: worker {pid} failed "
                  f"(rc={p.returncode}):\n{out[-2000:]}", flush=True)
            return None, rcs, outs
    for out in outs:
        for line in out.splitlines():
            if line.startswith("BENCH_MULTIHOST_RESULT "):
                return json.loads(line.split(" ", 1)[1]), rcs, outs
    print(f"  {strategy} x{nproc}: no result line emitted", flush=True)
    return None, rcs, outs


def run_sweep(strategies: typing.List[str], proc_counts: typing.List[int],
              steps: int, batch_per_slice: int, timeout: int) -> dict:
    report: dict = {
        "backend": "cpu", "devices_per_process": DEVICES_PER_PROCESS,
        "sequence_length": _SEQ, "steps_per_point": steps,
        "note": ("measured multi-process scaling on the CPU rig (gloo "
                 "collectives); weak scaling — global batch grows with the "
                 "data axis, per-chip work constant.  CPU numbers anchor "
                 "the SHAPE of the curve, not TPU magnitudes; on a box "
                 "with fewer cores than processes the efficiency column "
                 "additionally folds in core oversubscription (record "
                 "host_cores alongside when comparing rounds)."),
        "host_cores": os.cpu_count(),
        "strategies": {},
    }
    for strategy in strategies:
        rows = []
        base_per_chip = None
        for nproc in proc_counts:
            t0 = time.monotonic()
            row = _spawn_fleet(strategy, nproc, steps, batch_per_slice,
                               timeout)
            if row is None:
                rows.append({"processes": nproc, "error": "no result"})
                continue
            if "skipped" in row:
                print(f"  {strategy} x{nproc}: SKIPPED — {row['skipped']}",
                      flush=True)
                rows.append(row)
                # the gap is jax-version-, not process-count-, dependent:
                # one classified skip covers the strategy
                break
            if nproc == min(proc_counts) and row.get("tokens_per_sec_per_chip"):
                base_per_chip = row["tokens_per_sec_per_chip"]
            if base_per_chip:
                row["scaling_efficiency_vs_1proc"] = round(
                    row["tokens_per_sec_per_chip"] / base_per_chip, 3)
            print(f"  {strategy} x{nproc}: "
                  f"{row['tokens_per_sec_per_chip']} tok/s/chip "
                  f"(eff {row.get('scaling_efficiency_vs_1proc', 1.0)}) "
                  f"[{time.monotonic() - t0:.0f}s incl. compile]",
                  flush=True)
            rows.append(row)
        report["strategies"][strategy] = rows
    return report


def run_collectives_sweep(proc_counts: typing.List[int], steps: int,
                          sizes_mb: typing.List[float], timeout: int,
                          batch_per_slice: int) -> typing.List[dict]:
    """The collectives-only rows: all-reduce of gradient-sized buffers at
    each process count, no model step (docs/DISTRIBUTED.md 'Measured
    scaling')."""
    rows = []
    for nproc in proc_counts:
        t0 = time.monotonic()
        row = _spawn_fleet(
            "dp_tp", nproc, steps, batch_per_slice, timeout,
            extra_args=["--collectives",
                        "--sizes-mb", ",".join(str(s) for s in sizes_mb)])
        if row is None:
            rows.append({"processes": nproc, "error": "no result"})
            continue
        summary = " ".join(
            f"{r['size_mb']}MB={r['ms_per_allreduce']}ms" for r in row["rows"])
        print(f"  collectives x{nproc}: {summary} "
              f"[{time.monotonic() - t0:.0f}s]", flush=True)
        rows.append(row)
    return rows


def run_grad_ab_sweep(proc_counts: typing.List[int], steps: int,
                      batch_per_slice: int, timeout: int
                      ) -> typing.List[dict]:
    """The fused-vs-bucketed gradient-allreduce A/B on the dp_tp strategy
    (the one the bucketed policy targets), both legs at
    remat_policy=save_dots so the collective schedule is the only
    variable."""
    rows = []
    for nproc in proc_counts:
        pair: typing.Dict[str, typing.Any] = {"processes": nproc}
        for variant in ("fused", "bucketed"):
            t0 = time.monotonic()
            row = _spawn_fleet("dp_tp", nproc, steps, batch_per_slice,
                               timeout,
                               extra_args=["--grad-allreduce", variant])
            if row is None:
                pair[variant] = {"error": "no result"}
                continue
            pair[variant] = {k: row[k] for k in
                             ("tokens_per_sec", "tokens_per_sec_per_chip",
                              "wall_s", "loss") if k in row}
            print(f"  grad_ab {variant} x{nproc}: "
                  f"{row.get('tokens_per_sec_per_chip')} tok/s/chip "
                  f"[{time.monotonic() - t0:.0f}s incl. compile]",
                  flush=True)
        f = pair.get("fused", {}).get("tokens_per_sec_per_chip")
        b = pair.get("bucketed", {}).get("tokens_per_sec_per_chip")
        if f and b:
            pair["bucketed_vs_fused"] = round(b / f, 3)
        rows.append(pair)
    return rows


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--collectives", action="store_true",
                    help="(worker/sweep) collectives-only microbenchmark: "
                         "timed all-reduces of gradient-sized buffers, no "
                         "model step")
    ap.add_argument("--sizes-mb", default="1,4,16", dest="sizes_mb",
                    help="buffer sizes (MiB) for the collectives rows")
    ap.add_argument("--grad-allreduce", default="", dest="grad_allreduce",
                    choices=["", "fused", "bucketed"],
                    help="(worker) run the dp_tp step under this "
                         "grad_allreduce policy at remat_policy=save_dots")
    ap.add_argument("--grad-ab", action="store_true", dest="grad_ab",
                    help="run the fused-vs-bucketed grad-allreduce A/B "
                         "sweep on dp_tp (adds the grad_allreduce_ab rows)")
    ap.add_argument("--strategies", default="dp_tp,ring_sp,moe_ep,pp_gpipe")
    ap.add_argument("--procs", default="1,2,4,8")
    ap.add_argument("--steps", type=int, default=DEFAULT_STEPS)
    ap.add_argument("--batch-per-slice", type=int, default=8,
                    dest="batch_per_slice")
    ap.add_argument("--timeout", type=int, default=600,
                    help="seconds per (strategy, nproc) fleet")
    ap.add_argument("--skip-strategy-sweep", action="store_true",
                    dest="skip_strategy_sweep",
                    help="only run the requested extra sweeps "
                         "(--grad-ab / --collectives), merging into --out")
    ap.add_argument("--out", default=os.path.join(HERE, "..",
                                                  "MULTICHIP_MEASURED.json"))
    args = ap.parse_args()
    sizes_mb = [float(s) for s in args.sizes_mb.split(",") if s]
    strategies = [s for s in args.strategies.split(",") if s]
    unknown = [s for s in strategies if s not in STRATEGIES]
    if unknown:
        ap.error(f"unknown strategies {unknown}; have {list(STRATEGIES)}")
    if args.worker:
        if args.collectives:
            return collectives_worker(args.steps, sizes_mb)
        return worker(strategies[0], args.steps, args.batch_per_slice,
                      grad_allreduce=args.grad_allreduce)
    proc_counts = sorted(int(p) for p in args.procs.split(","))
    out = os.path.abspath(args.out)
    if args.skip_strategy_sweep:
        # merge the extra sweeps into the existing report; a missing --out
        # starts one from scratch rather than running the multi-hour
        # strategy sweep the flag explicitly asked to skip
        report = {}
        if os.path.exists(out):
            with open(out) as f:
                report = json.load(f)
    else:
        report = run_sweep(strategies, proc_counts, args.steps,
                           args.batch_per_slice, args.timeout)
    if args.collectives or not args.skip_strategy_sweep:
        report["collectives"] = run_collectives_sweep(
            proc_counts, max(args.steps, 8), sizes_mb, args.timeout,
            args.batch_per_slice)
    if args.grad_ab:
        report["grad_allreduce_ab"] = run_grad_ab_sweep(
            [p for p in proc_counts if p > 1] or proc_counts,
            args.steps, args.batch_per_slice, args.timeout)
    with open(out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
    print(f"wrote {out}")
    # the sweep above forced the CPU rig (gloo/TCP, virtual devices): keep
    # the silicon queue loud, like bench_decode.py --tpu-recheck does
    print("NOTE: CPU-rig measurement — gloo/TCP collectives on an "
          "oversubscribed host anchor the curve SHAPE, not TPU "
          "magnitudes.  Queued on silicon (BASELINE.md 'Queued on "
          "silicon'): the per-strategy 1-to-8-chip curve, the "
          "fused-vs-bucketed grad-allreduce A/B (--grad-ab), and the "
          "collectives-only rows (--collectives) on ICI.", flush=True)
    measured = [s for s, rows in report["strategies"].items()
                if any("tokens_per_sec_per_chip" in r for r in rows)]
    skipped = [s for s, rows in report["strategies"].items()
               if any("skipped" in r for r in rows)]
    failed = [s for s, rows in report["strategies"].items()
              if s not in measured and s not in skipped]
    print(f"measured: {measured}; skipped (env gap): {skipped}; "
          f"failed: {failed}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
