#!/usr/bin/env python3
"""Hyperparameter sweep runner.

Equivalent of /root/reference/scripts/run_experiments.py: takes a base config
plus a sweep config (dict of key -> list of values), forms the cartesian
product, writes one JSON config per combination into ``buffer_configs/``, and
launches each run — either directly, under ``run_manager.py`` (preemption
recovery), or in a detached ``screen`` session per accelerator like the
reference.  TPU creation commands are pluggable strings with ``{name}``
placeholders instead of the reference's hard-coded gcloud v1.15 calls.
"""
import argparse
import hashlib
import itertools
import json
import os
import shutil
import subprocess
import sys
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--base_config", required=True)
    ap.add_argument("--run_config", default="",
                    help="JSON of {key: [values...]} to sweep")
    ap.add_argument("--run_name_prefix", default="runs/sweep/")
    ap.add_argument("--number_of_repetitions", type=int, default=1)
    ap.add_argument("--repetition_start_idx", type=int, default=0)
    ap.add_argument("--buffer_dir", default="buffer_configs")
    ap.add_argument("--launcher", choices=["inline", "screen", "manager", "print"],
                    default="print")
    ap.add_argument("--create_cmd_template", default="",
                    help="e.g. 'gcloud compute tpus tpu-vm create {name} ...'")
    ap.add_argument("--delete_cmd_template", default="")
    ap.add_argument("--health_cmd_template", default="")
    ap.add_argument("--tpu_start_id", type=int, default=0)
    ap.add_argument("--start_up_sleep", type=int, default=0)
    args = ap.parse_args()

    with open(args.base_config) as f:
        base_config = json.load(f)
    sweep = {}
    if args.run_config:
        with open(args.run_config) as f:
            sweep = json.load(f)

    os.makedirs(args.buffer_dir, exist_ok=True)
    keys = list(sweep.keys())
    combos = list(itertools.product(*[range(len(sweep[k])) for k in keys])) or [()]
    main_py = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "main.py")

    tpu_id = args.tpu_start_id
    for pos in combos:
        cfg = dict(base_config)
        for idx, key in enumerate(keys):
            cfg[key] = sweep[key][pos[idx]]
        for rep in range(args.repetition_start_idx, args.number_of_repetitions):
            run_name = "-".join(f"{k}={cfg[k]}" for k in keys) + f"-run={rep}"
            run_name = (run_name.replace(" ", "_").replace("'", "")
                        .replace(":", "=").replace(",", "-")
                        .replace("[", "|").replace("]", "|"))
            cfg["model_path"] = args.run_name_prefix + run_name
            cfg_path = os.path.join(args.buffer_dir, f"{tpu_id}_{run_name}.json")
            with open(cfg_path, "w") as w:
                json.dump(cfg, w, indent=2)

            name = f"exp-{tpu_id}"
            train_cmd = f"{sys.executable} {main_py} --model {cfg_path} --run_mode train"
            if args.launcher == "inline":
                subprocess.run(train_cmd, shell=True)
            elif args.launcher == "manager":
                mgr = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "run_manager.py")
                cmd = [sys.executable, mgr, train_cmd,
                       "--model-path", cfg["model_path"]]
                for flag, tmpl in (("--create-cmd", args.create_cmd_template),
                                   ("--delete-cmd", args.delete_cmd_template),
                                   ("--health-cmd", args.health_cmd_template)):
                    if tmpl:
                        cmd += [flag, tmpl.format(name=name)]
                subprocess.Popen(cmd)
            elif args.launcher == "screen" and shutil.which("screen"):
                session = run_name if len(run_name) <= 66 else \
                    hashlib.sha256(run_name.encode()).hexdigest()
                subprocess.run(["screen", "-dmS", f"tpu_id:{tpu_id}--{session}",
                                "bash", "-c", train_cmd])
            else:
                print(train_cmd)
            tpu_id += 1
            time.sleep(args.start_up_sleep)


if __name__ == "__main__":
    main()
