#!/usr/bin/env bash
# Late-marker test runner (docs: README 'Tests').
#
# Tier-1 on this box truncates at the 870 s timeout (ROADMAP 'Tier-1
# verify'), which silently hides the marker suites that collect AFTER the
# cutoff — they all pass standalone, but the tier-1 log never shows them.
# This script runs each post-truncation suite standalone and prints a
# per-suite pass/fail summary, so "tier-1 green" stops being the only
# (incomplete) signal.
#
#   scripts/run_late_markers.sh                   # the full late set
#   scripts/run_late_markers.sh serving router    # a subset
#   LATE_MARKER_TIMEOUT=1200 scripts/run_late_markers.sh   # per-suite cap
set -u
cd "$(dirname "$0")/.."

MARKERS=("$@")
if [ ${#MARKERS[@]} -eq 0 ]; then
  MARKERS=(serving contbatch distributed specdecode specpaged
           staticanalysis attribution pagedkv router elastic forensics
           disagg conc)
fi
PER_SUITE_TIMEOUT="${LATE_MARKER_TIMEOUT:-900}"
# the elastic suite runs two full controller e2es (multiple jax fleet
# generations each) — it needs more than the shared default on this box
ELASTIC_SUITE_TIMEOUT="${LATE_MARKER_ELASTIC_TIMEOUT:-1800}"

declare -a RESULTS
rc_all=0
for m in "${MARKERS[@]}"; do
  log="/tmp/late_marker_${m}.log"
  t0=$(date +%s)
  t="$PER_SUITE_TIMEOUT"
  [ "$m" = elastic ] && t="$ELASTIC_SUITE_TIMEOUT"
  timeout -k 10 "$t" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m "$m" \
    -p no:cacheprovider -p no:randomly >"$log" 2>&1
  rc=$?
  dt=$(( $(date +%s) - t0 ))
  line=$(grep -aE '^[0-9]+ (passed|failed)' "$log" | tail -1)
  [ -z "$line" ] && line=$(tail -1 "$log")
  if [ "$rc" -eq 0 ]; then
    status=PASS
  elif [ "$rc" -ge 124 ] && [ "$rc" -le 137 ]; then
    status=TIMEOUT; rc_all=1
  else
    status=FAIL; rc_all=1
  fi
  RESULTS+=("$(printf '%-7s %5ss  %-14s %s' "$status" "$dt" "$m" "$line")")
  printf '%-7s %5ss  %-14s %s\n' "$status" "$dt" "$m" "$line"
done

echo
echo "== late-marker summary (logs: /tmp/late_marker_<suite>.log) =="
printf '%s\n' "${RESULTS[@]}"
exit $rc_all
