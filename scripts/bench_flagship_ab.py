#!/usr/bin/env python3
"""Flagship (32big_mixer) structural A/B harness — round 5's attack on the
26.4k tokens/sec plateau (VERDICT r4 next-round #2).

The round-2/3 traces bound the recipe at XLA's fusion plan: dot fusions
55%, weight-grad reductions 22% (measured NON-separable — the pallas norm
backward regressed 24%, docs/PERFORMANCE.md round 3), backward ≈ 74% of
the step with revnet's recompute making it structurally ~3.2× forward.
The remaining levers are STRUCTURAL, not kernel-level: how much recompute
the backward performs (memory strategy), how often the scan-over-layers
round-trips the shared-weight gradient accumulator (scan_unroll), and the
batch/memory trade those choices unlock.  This harness measures each
variant in a fresh subprocess (clean HBM) and prints one JSON line per
variant plus a ranked summary.

Usage: python scripts/bench_flagship_ab.py [--variants name,name,...]
"""
import argparse
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

# name -> config overrides on bench.py's BENCH_CONFIG
VARIANTS = {
    "baseline": {},
    # fewer scan iterations -> fewer shared-grad accumulator round-trips
    # (the 'shared' attention weights accumulate cotangents across all 32
    # depth iterations of the backward scan)
    "unroll2": {"scan_unroll": 2},
    "unroll4": {"scan_unroll": 4},
    "unroll8": {"scan_unroll": 8},
    # no recompute at all: backward drops from ~3.2x fwd toward ~2x fwd if
    # the stacked residuals fit; the scan stores per-layer carries
    "none_b32": {"memory_reduction_strategy": "none"},
    "none_b16": {"memory_reduction_strategy": "none", "train_batch_size": 16},
    "ckpt_b32": {"memory_reduction_strategy": "checkpoint"},
    # momentum strategy: same invertibility class as revnet, one stream
    "momentum_b32": {"memory_reduction_strategy": "momentum"},
    # revnet without scan (unrolled): lets XLA fuse across block boundaries
    # at the cost of compile time; round 1 measured scan ~= unrolled but
    # that predates the fused-norm/backward work
    "unrolled_b32": {"scan_layers": False},
    # larger batch under revnet: amortise per-step fixed costs (scan
    # carries, optimizer, infeed) over more tokens if the transient
    # attention maps still fit
    "revnet_b48": {"train_batch_size": 48},
    "revnet_b64": {"train_batch_size": 64},
    "revnet_b96": {"train_batch_size": 96},
    "revnet_b128": {"train_batch_size": 128},
}

WORKER = r"""
import json, os, sys, time
sys.path.insert(0, os.path.join(%(here)r, ".."))
import numpy as np
import jax
import jax.numpy as jnp
sys.path.insert(0, %(here)r)
from homebrewnlp_tpu.config import ModelParameter
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer
sys.path.insert(0, os.path.join(%(here)r, ".."))
import importlib
bench = importlib.import_module("bench")

cfg = dict(bench.BENCH_CONFIG)
cfg.update(json.loads(%(overrides)r))
cfg["model_path"] = "/tmp/bench_ab_run"
params = ModelParameter(cfg)
model = Model(params)
trainer = Trainer(params, model)
rng = np.random.default_rng(0)

def make_batch():
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    return {"token_x": jnp.asarray(x),
            "token_y": jnp.asarray((x + 1) %% params.vocab_size)}

state = trainer.init_state(make_batch())
for _ in range(2):
    state, metrics = trainer.step(state, make_batch())
float(metrics["loss"])
batches = [make_batch() for _ in range(10)]
t0 = time.monotonic()
for b in batches:
    state, metrics = trainer.step(state, b)
final = float(metrics["loss"])
dt = time.monotonic() - t0
tokens = 10 * params.train_batch_size * params.sequence_length
print(json.dumps({"variant": %(name)r,
                  "tokens_per_sec_chip": round(tokens / dt, 1),
                  "ms_per_step": round(dt * 100, 1),
                  "batch": params.train_batch_size,
                  "final_loss": final}))
"""


def run_variant(name: str, overrides: dict, timeout: int = 900):
    code = WORKER % {"here": HERE, "overrides": json.dumps(overrides),
                     "name": name}
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"variant": name, "error": "timeout"}
    out = None
    for line in proc.stdout.splitlines():
        try:
            out = json.loads(line)
        except ValueError:
            continue
    if out is None:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        return {"variant": name, "error": f"rc={proc.returncode}",
                "stderr_tail": tail}
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--variants", default=",".join(VARIANTS))
    args = ap.parse_args()
    results = []
    for name in args.variants.split(","):
        name = name.strip()
        if name not in VARIANTS:
            print(f"unknown variant {name!r}", file=sys.stderr)
            continue
        res = run_variant(name, VARIANTS[name])
        print(json.dumps(res), flush=True)
        results.append(res)
    ok = [r for r in results if "tokens_per_sec_chip" in r]
    ok.sort(key=lambda r: -r["tokens_per_sec_chip"])
    print(json.dumps({"ranked": [(r["variant"], r["tokens_per_sec_chip"])
                                 for r in ok]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
