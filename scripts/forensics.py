#!/usr/bin/env python3
"""Merge per-process flight-recorder blackboxes into ONE causally-ordered
incident timeline (docs/OBSERVABILITY.md 'Flight recorder').

Every process of a run — train ranks (``blackbox_p<rank>.jsonl``), the
serving router (``blackbox_router.jsonl``), replicas and their HTTP
children — dumps a bounded ring of typed events on every exit path.  Each
file is internally ordered (per-process ``seq``), but wall clocks skew
across hosts, so a naive sort-by-timestamp can invert cause and effect.
This tool orders CAUSALLY:

* within a process, events keep their sequence order;
* across processes, a lease scan that OBSERVED peer p's beat s
  happened-after p recorded beat s (the coordination-KV ordering the
  elastic agents already establish) — these edges pin the cross-process
  skeleton, and the wall clock only breaks the remaining ties.

The incident summary names the FIRST-FAILING rank: a rank that peers
declared lapsed but that recorded no exit of its own (its blackbox — if
one exists at all — ends mid-flight) was killed from outside; survivors'
membership records show, in causal order, who noticed first and how the
pod died.

Usage::

    python scripts/forensics.py <model_path>                # the timeline
    python scripts/forensics.py <model_path> --json         # machine form
    python scripts/forensics.py <model_path> --trace <id>   # one request
    python scripts/forensics.py file1.jsonl file2.jsonl     # explicit set

Stdlib-only and jax-free: runs on a laptop against blackboxes rsynced off
a dead pod.
"""
from __future__ import annotations

import argparse
import glob
import heapq
import json
import os
import sys
import typing


def load_blackbox(path: str) -> typing.Tuple[str, typing.List[dict]]:
    """One blackbox file -> (tag, events).  The header line names the tag;
    malformed lines are skipped rather than failing the merge (a file torn
    mid-write is exactly the incident case)."""
    tag = os.path.basename(path)
    if tag.startswith("blackbox_"):
        tag = tag[len("blackbox_"):]
    if tag.endswith(".jsonl"):
        tag = tag[:-len(".jsonl")]
    events: typing.List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "blackbox" in obj:
                tag = obj["blackbox"].get("tag") or tag
                continue
            if "kind" in obj:
                events.append(obj)
    return tag, events


def load_files(paths: typing.Sequence[str]) -> typing.Dict[str, list]:
    files: typing.Dict[str, list] = {}
    for path in paths:
        tag, events = load_blackbox(path)
        files.setdefault(tag, []).extend(events)
    return files


def discover(model_path: str) -> typing.List[str]:
    return sorted(glob.glob(os.path.join(model_path, "blackbox_*.jsonl")))


# ---- causal merge -----------------------------------------------------------

def causal_order(files: typing.Dict[str, typing.List[dict]]
                 ) -> typing.List[dict]:
    """Merge per-process event lists into one order: per-process sequence +
    beat->observation edges, wall-clock tie-break (Kahn's algorithm over a
    happens-before DAG, ready set keyed by wall time so the output is
    deterministic and readable)."""
    nodes: typing.List[typing.Tuple[str, int]] = []
    events: typing.Dict[typing.Tuple[str, int], dict] = {}
    for tag, evs in files.items():
        for i, ev in enumerate(sorted(evs, key=lambda e: e.get("seq", 0))):
            node = (tag, i)
            nodes.append(node)
            events[node] = dict(ev, proc=ev.get("proc", tag))
    succ: typing.Dict[tuple, typing.List[tuple]] = {n: [] for n in nodes}
    indeg: typing.Dict[tuple, int] = {n: 0 for n in nodes}

    def edge(a: tuple, b: tuple) -> None:
        succ[a].append(b)
        indeg[b] += 1

    for tag, evs in files.items():
        count = sum(1 for n in nodes if n[0] == tag)
        for i in range(count - 1):
            edge((tag, i), (tag, i + 1))
    # beat index: rank -> sorted [(beat seq, node)]
    beats: typing.Dict[int, typing.List[typing.Tuple[int, tuple]]] = {}
    for node in nodes:
        ev = events[node]
        if ev.get("kind") == "beat" and "rank" in ev and "beat" in ev:
            beats.setdefault(int(ev["rank"]), []).append(
                (int(ev["beat"]), node))
    for v in beats.values():
        v.sort()
    for node in nodes:
        ev = events[node]
        if ev.get("kind") != "lease_scan":
            continue
        for pid_s, seen_seq in (ev.get("peers") or {}).items():
            try:
                pid, seen_seq = int(pid_s), int(seen_seq)
            except (TypeError, ValueError):
                continue
            # the LATEST beat at/below the observed seq happened-before
            # this scan (the killed rank's file may be missing — no edge)
            best = None
            for bseq, bnode in beats.get(pid, ()):
                if bseq <= seen_seq:
                    best = bnode
                else:
                    break
            if best is not None and best[0] != node[0]:
                edge(best, node)
    ready = [( events[n].get("wall", 0.0), events[n].get("seq", 0), n)
             for n in nodes if indeg[n] == 0]
    heapq.heapify(ready)
    out: typing.List[dict] = []
    while ready:
        _, _, node = heapq.heappop(ready)
        out.append(events[node])
        for nxt in succ[node]:
            indeg[nxt] -= 1
            if indeg[nxt] == 0:
                heapq.heappush(ready, (events[nxt].get("wall", 0.0),
                                       events[nxt].get("seq", 0), nxt))
    if len(out) < len(nodes):  # a cycle (clock-skewed duplicate files):
        seen = {id(e) for e in out}  # degrade to wall order, never crash
        rest = [events[n] for n in nodes if id(events[n]) not in seen]
        out.extend(sorted(rest, key=lambda e: e.get("wall", 0.0)))
    return out


# ---- incident analysis ------------------------------------------------------

def analyze(files: typing.Dict[str, typing.List[dict]]) -> dict:
    """The incident summary: first-failing rank(s), per-survivor lapse
    observations in causal order, membership exits, stragglers."""
    timeline = causal_order(files)
    # the INCIDENT generation: a rank killed before its new incarnation's
    # first flush leaves its PREVIOUS generation's ring (ending in a clean
    # exit) on disk — exits and lapse records from older generations must
    # not exonerate it, so everything below filters to the newest
    # generation any membership event names (None = no gen stamps at all)
    gens = [ev.get("gen") for ev in timeline
            if ev.get("kind") == "membership" and ev.get("gen") is not None]
    incident_gen = max(gens) if gens else None

    def _in_incident(ev: dict) -> bool:
        return incident_gen is None or ev.get("gen") is None \
            or ev.get("gen") == incident_gen

    exits: typing.Dict[str, dict] = {}
    memberships: typing.List[dict] = []
    stragglers: typing.List[dict] = []
    lapsed_named: typing.Set[int] = set()
    for ev in timeline:
        kind = ev.get("kind")
        if kind == "exit":
            if incident_gen is not None \
                    and ev.get("gen") != incident_gen:
                continue  # a stale prior-generation ring's clean exit
            exits[ev.get("proc", "?")] = ev
        elif kind == "membership":
            if not _in_incident(ev):
                continue
            memberships.append(ev)
            for pid in ev.get("lapsed") or []:
                try:
                    lapsed_named.add(int(pid))
                except (TypeError, ValueError):
                    pass
        elif kind == "straggler":
            stragglers.append(ev)
    # a lapsed rank with NO exit record of its own died from outside — the
    # first-failing rank.  Its blackbox (if any survived an earlier flush)
    # simply stops; survivors' exits are 143/144/crash records.
    exited_ranks: typing.Set[int] = set()
    for ev in exits.values():
        if "rank" in ev:
            try:
                exited_ranks.add(int(ev["rank"]))
            except (TypeError, ValueError):
                pass
    killed = sorted(lapsed_named - exited_ranks)
    observations = [{"observer": ev.get("proc"), "cause": ev.get("cause"),
                     "lapsed": ev.get("lapsed"), "wall": ev.get("wall")}
                    for ev in memberships]
    return {
        "processes": sorted(files),
        "events": len(timeline),
        "first_failing_rank": killed[0] if killed else None,
        "killed_ranks": killed,
        "lapse_observations": observations,
        "membership_exits": [
            {"proc": tag, "code": ev.get("code"), "path": ev.get("path"),
             "reason": ev.get("reason"), "cause": ev.get("cause")}
            for tag, ev in sorted(exits.items())
            if ev.get("code") == 144 or ev.get("path") == "force"],
        "exits": {tag: {"code": ev.get("code"),
                        "path": ev.get("path") or ev.get("reason")}
                  for tag, ev in sorted(exits.items())},
        "stragglers": [{"rank": ev.get("rank"),
                        "stall_s": ev.get("stall_s")} for ev in stragglers],
        "timeline": timeline,
    }


_VERBOSE_FIELDS = ("kind", "proc", "seq", "t", "wall")


def format_timeline(timeline: typing.Sequence[dict],
                    limit: int = 0) -> str:
    """Human form: one line per event, relative wall time, the process it
    came from, and the payload fields."""
    if not timeline:
        return "(no events)"
    base = min(ev.get("wall", 0.0) for ev in timeline)
    lines = []
    shown = timeline if not limit else timeline[-limit:]
    if limit and len(timeline) > limit:
        lines.append(f"... ({len(timeline) - limit} earlier events elided; "
                     "use --limit 0 for all)")
    for ev in shown:
        rel = ev.get("wall", base) - base
        fields = " ".join(f"{k}={ev[k]!r}" for k in sorted(ev)
                          if k not in _VERBOSE_FIELDS)
        lines.append(f"[+{rel:9.3f}s] {ev.get('proc', '?'):<10} "
                     f"{ev.get('kind', '?'):<18} {fields}")
    return "\n".join(lines)


def format_report(report: dict, limit: int = 0) -> str:
    lines = ["== forensics: merged flight-recorder timeline ==",
             f"processes: {', '.join(report['processes'])} "
             f"({report['events']} events)"]
    if report["first_failing_rank"] is not None:
        lines.append(f"FIRST-FAILING RANK: p{report['first_failing_rank']} "
                     "(declared lapsed by peers, no exit record of its own "
                     "— killed from outside)")
        if len(report["killed_ranks"]) > 1:
            lines.append(f"  (all killed ranks: "
                         f"{report['killed_ranks']})")
    else:
        lines.append("no killed rank identified (no lapse without a "
                     "matching exit record)")
    if report["lapse_observations"]:
        lines.append("lapse observations (causal order):")
        for i, obs in enumerate(report["lapse_observations"]):
            lines.append(f"  {i + 1}. {obs['observer']}: {obs['cause']} "
                         f"(lapsed={obs['lapsed']})")
    if report["membership_exits"]:
        lines.append("membership exits (144 / force path):")
        for ex in report["membership_exits"]:
            lines.append(f"  {ex['proc']}: code={ex['code']} "
                         f"path={ex['path']}")
    if report["stragglers"]:
        lines.append("straggler flags: " + ", ".join(
            f"p{s['rank']} (+{s['stall_s']}s)"
            for s in report["stragglers"]))
    lines.append("")
    lines.append(format_timeline(report["timeline"], limit=limit))
    return "\n".join(lines)


# ---- per-request trace merge (--trace) --------------------------------------

def trace_report(files: typing.Dict[str, typing.List[dict]],
                 trace_id: str,
                 model_path: typing.Optional[str] = None) -> dict:
    """All spans of one trace id across every process's events (plus the
    per-request export under <model_path>/traces when present), as one
    merged per-request view with the per-hop breakdown."""
    spans: typing.List[dict] = []
    for tag, evs in files.items():
        for ev in evs:
            if ev.get("kind") == "span" and ev.get("trace") == trace_id:
                spans.append({"name": ev.get("name", "?"),
                              "t0": float(ev.get("t0", 0.0)),
                              "dur": float(ev.get("dur", 0.0)),
                              "proc": ev.get("proc", tag)})
    exported = None
    if model_path:
        path = os.path.join(model_path, "traces", f"trace_{trace_id}.json")
        if os.path.exists(path):
            with open(path) as f:
                exported = json.load(f)
    hops: typing.Dict[str, float] = {}
    for s in spans:
        key = s["name"].split("/", 1)[1] if s["name"].startswith("chunk/") \
            else s["name"]
        hops[key] = round(hops.get(key, 0.0) + s["dur"], 6)
    return {"trace_id": trace_id, "spans": sorted(spans,
                                                  key=lambda s: s["t0"]),
            "hops": hops, "exported": exported}


def format_trace(report: dict) -> str:
    lines = [f"== trace {report['trace_id']} =="]
    for s in report["spans"]:
        lines.append(f"  [{s['t0']:14.6f} +{s['dur'] * 1e3:9.3f}ms] "
                     f"{s['proc']:<12} {s['name']}")
    lines.append("per-hop totals (seconds):")
    for k, v in sorted(report["hops"].items()):
        lines.append(f"  {k:<16} {v:.6f}")
    return "\n".join(lines)


def main(argv: typing.Optional[typing.Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("inputs", nargs="+",
                    help="a model_path (blackbox_*.jsonl discovered inside)"
                         " or explicit blackbox files")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--trace", default=None,
                    help="merge ONE request's spans instead of the "
                         "incident timeline")
    ap.add_argument("--limit", type=int, default=200,
                    help="show at most the last N timeline events "
                         "(0 = all)")
    args = ap.parse_args(argv)
    paths: typing.List[str] = []
    model_path = None
    for inp in args.inputs:
        if os.path.isdir(inp):
            model_path = model_path or inp
            found = discover(inp)
            if not found:
                print(f"forensics: no blackbox_*.jsonl under {inp}",
                      file=sys.stderr)
                return 2
            paths.extend(found)
        elif os.path.exists(inp):
            paths.append(inp)
        else:
            print(f"forensics: no such file or directory: {inp}",
                  file=sys.stderr)
            return 2
    files = load_files(paths)
    if not any(files.values()):
        print("forensics: blackbox files held no events", file=sys.stderr)
        return 2
    if args.trace:
        report = trace_report(files, args.trace, model_path=model_path)
        if not report["spans"] and report["exported"] is None:
            print(f"forensics: no spans for trace {args.trace!r}",
                  file=sys.stderr)
            return 2
        print(json.dumps(report, indent=2) if args.json
              else format_trace(report))
        return 0
    report = analyze(files)
    if args.json:
        out = dict(report)
        out["timeline"] = out["timeline"][-args.limit:] if args.limit \
            else out["timeline"]
        print(json.dumps(out, indent=2))
    else:
        print(format_report(report, limit=args.limit))
    return 0


if __name__ == "__main__":
    sys.exit(main())
