#!/usr/bin/env python3
"""BPE tokenizer trainer.

Equivalent of the reference's Cython trainer
(/root/reference/scripts/train_tokenizer.pyx): trains a 65,536-vocab BPE with
the same construction — unk token '\\x01', byte specials chr(0..255), and the
"isolated" Split pre-tokenizer over the digits/whitespace/punctuation regex
(train_tokenizer.pyx:180-188) — then writes ``tokenizer.json``.  The
reference's surrounding Cython machinery streamed The Pile from the network;
this trains from local text/jsonl files (zero-egress image).

Two backends:
- ``native`` (default): the C++ trainer (native/bpe_trainer.cpp) — the
  rebuild's equivalent of the reference's gcc-compiled Cython hot path —
  byte-level merge training with multithreaded word counting.  jsonl inputs
  are streamed to a raw-text spool first.
- ``hf``: the HuggingFace ``tokenizers`` trainer fed through a multiprocess
  chunk-reader pool.
"""
import argparse
import json
import multiprocessing
import os
import sys


def _read_chunks(path: str, chunk_bytes: int):
    if path.endswith(".jsonl"):
        with open(path, errors="ignore") as f:
            buf = []
            size = 0
            for line in f:
                try:
                    text = json.loads(line).get("text", "")
                except json.JSONDecodeError:
                    continue
                buf.append(text)
                size += len(text)
                if size >= chunk_bytes:
                    yield "\n".join(buf)
                    buf, size = [], 0
            if buf:
                yield "\n".join(buf)
    else:
        with open(path, errors="ignore") as f:
            while True:
                chunk = f.read(chunk_bytes)
                if not chunk:
                    return
                yield chunk


def _worker(paths, queue, chunk_bytes):
    for path in paths:
        for chunk in _read_chunks(path, chunk_bytes):
            queue.put(chunk)
    queue.put(None)


sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _native_main(args) -> bool:
    from homebrewnlp_tpu.data import native_bpe
    if not native_bpe.available():
        return False
    import tempfile
    paths, spools = [], []
    try:
        for path in args.inputs:
            if path.endswith(".jsonl"):
                spool = tempfile.NamedTemporaryFile(
                    mode="w", suffix=".txt", delete=False, errors="ignore")
                spools.append(spool.name)
                for chunk in _read_chunks(path, args.chunk_bytes):
                    spool.write(chunk)
                    spool.write("\n")
                spool.close()
                paths.append(spool.name)
            else:
                paths.append(path)
        vocab = native_bpe.train_tokenizer_file(
            paths, args.vocab_size, args.output, n_threads=args.processes)
        print(f"wrote {args.output} (vocab {vocab}, native trainer)")
        return True
    finally:
        for spool in spools:
            os.unlink(spool)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("inputs", nargs="+", help="text or jsonl files")
    ap.add_argument("--vocab-size", type=int, default=65536)
    ap.add_argument("--output", default="tokenizer.json")
    ap.add_argument("--processes", type=int, default=4)
    ap.add_argument("--chunk-bytes", type=int, default=1 << 20)
    ap.add_argument("--backend", choices=["native", "hf"], default="native")
    args = ap.parse_args()

    if args.backend == "native":
        if _native_main(args):
            return
        print("native trainer unavailable; falling back to hf", file=sys.stderr)

    from tokenizers import Regex, Tokenizer
    from tokenizers.models import BPE
    from tokenizers.pre_tokenizers import Split
    from tokenizers.trainers import BpeTrainer
    from homebrewnlp_tpu.data import native_bpe

    regex = Regex(native_bpe.split_regex())
    tokenizer = Tokenizer(BPE(unk_token="\x01"))
    tokenizer.pre_tokenizer = Split(regex, "isolated")
    trainer = BpeTrainer(special_tokens=[chr(i) for i in range(256)],
                         vocab_size=args.vocab_size)

    nproc = min(args.processes, len(args.inputs))
    if nproc > 1:
        manager = multiprocessing.Manager()
        queue = manager.Queue(maxsize=64)
        shards = [args.inputs[i::nproc] for i in range(nproc)]
        procs = [multiprocessing.Process(target=_worker,
                                         args=(shard, queue, args.chunk_bytes))
                 for shard in shards]
        for p in procs:
            p.start()

        def iterator():
            done = 0
            while done < len(procs):
                item = queue.get()
                if item is None:
                    done += 1
                    continue
                yield item

        tokenizer.train_from_iterator(iterator(), trainer)
        for p in procs:
            p.join()
    else:
        def iterator():
            for path in args.inputs:
                yield from _read_chunks(path, args.chunk_bytes)
        tokenizer.train_from_iterator(iterator(), trainer)

    tmp = args.output + ".tmp"
    tokenizer.save(tmp)
    with open(tmp, errors="ignore") as r, open(args.output, "w", errors="ignore") as w:
        w.write(json.dumps(json.loads(r.read()), indent=4))
    os.remove(tmp)
    print(f"wrote {args.output} (vocab {args.vocab_size})")


if __name__ == "__main__":
    main()
