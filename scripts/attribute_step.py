#!/usr/bin/env python3
"""Attribute profiler trace time to model scopes and join the cost ledger.

Usage:
    python scripts/attribute_step.py <trace_dir_or_trace.json.gz>
        [--entry train_step] [--steps N] [--hlo FILE] [--ledger FILE]
        [--top K]

The "top offenders" table ROADMAP item 2 calls for: per named scope (the
``jax.named_scope`` regions core/scope.py mirrors into the model graph),

    measured device-time share  vs  FLOPs share  vs  bytes share,

joined from three artifacts:

1. the trace — device events carry the HLO instruction name
   (``args.hlo_op``);
2. the compiled entry point — its HLO text maps instruction ->
   ``metadata op_name`` -> scope (``--hlo`` loads a saved ``.as_text()``
   dump; default recompiles the audit entry on this backend, which matches
   a trace captured from the same config/jax/backend);
3. the committed cost ledger (``analysis/cost_ledger.json``) — per-scope
   FLOPs/bytes shares and roofline bound.

Scopes whose time share exceeds BOTH their FLOPs and bytes share are
flagged ``<<`` — time spent neither computing nor moving the bytes the
model asked for is pure overhead, the first place ROADMAP item 2's
0.38 -> 0.55+ MFU hunt should look.

Fails loudly (nonzero exit naming the file) on a trace with zero
device-side events or one that never ran the requested entry's module.
"""
import argparse
import collections
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import analyze_trace  # noqa: E402  (sibling script: loader + loud failures)

#: entry point -> compiled module name (the ``HloModule <name>`` the trace
#: tags device events with via ``args.hlo_module``)
ENTRY_MODULES = {
    "train_step": "jit_step_fn",
    "decode_chunk_step": "jit_step",
    "prefill_entry_step": "jit_step",
    "eval_fn": "jit_eval_fn",
}


def module_of(hlo_text: str) -> str:
    """The module name off the ``HloModule <name>`` header line."""
    for line in hlo_text.splitlines():
        if line.startswith("HloModule"):
            return line.split()[1].rstrip(",")
    return ""


def attribute(events, hlo_text: str, ledger_entry=None):
    """``(rows, unattributed_share, total_us)`` — rows are dicts with
    scope/time_share/flops_share/bytes_share/bound/overhead, sorted by time
    share.  Pure function over loaded data (unit-tested on a fixture)."""
    from homebrewnlp_tpu.analysis import cost_ledger
    table = cost_ledger.instruction_table(hlo_text)
    per_scope, unattr, total = cost_ledger.attribute_events(events, table)
    scopes = (ledger_entry or {}).get("scopes", {})
    rows = []
    for scope, dur in sorted(per_scope.items(), key=lambda kv: -kv[1]):
        share = dur / total if total else 0.0
        led = scopes.get(scope, {})
        fs = led.get("flops_share")
        bs = led.get("bytes_share")
        overhead = (scope != "unattributed" and fs is not None
                    and bs is not None
                    and share > fs + 0.02 and share > bs + 0.02)
        rows.append({"scope": scope, "time_us": dur, "time_share": share,
                     "flops_share": fs, "bytes_share": bs,
                     "bound": led.get("bound"), "overhead": overhead})
    unattributed = per_scope.get("unattributed", 0.0) / total if total \
        else 0.0
    return rows, unattributed, total


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("trace", help="trace dir or *.trace.json.gz")
    ap.add_argument("--entry", default="train_step",
                    choices=sorted(ENTRY_MODULES),
                    help="which audited entry point the trace ran")
    ap.add_argument("--steps", type=int, default=1,
                    help="traced step count (ms/step normalisation)")
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--hlo", default=None,
                    help="saved compiled-HLO text of the traced program "
                         "(e.g. <model_path>/train_step.stablehlo.txt is "
                         "NOT it — use compiled .as_text(); default: "
                         "recompile the audit entry on this backend)")
    ap.add_argument("--ledger", default=None,
                    help="alternate cost_ledger.json")
    args = ap.parse_args(argv)

    trace_file = analyze_trace.resolve_trace_file(args.trace)
    evs = analyze_trace.device_events(analyze_trace.load_events(args.trace))
    if not evs:
        raise SystemExit(f"{trace_file}: trace contains zero device-side "
                         "events (args.hlo_op) — empty capture window, or "
                         "host-only trace?")

    if args.hlo:
        with open(args.hlo) as f:
            hlo = f.read()
        module = module_of(hlo) or ENTRY_MODULES[args.entry]
    else:
        from homebrewnlp_tpu.analysis import entry_points
        print(f"compiling audit entry {args.entry!r} for the instruction->"
              "scope map...", file=sys.stderr)
        hlo, _ = entry_points.lower_one(args.entry)
        module = module_of(hlo) or ENTRY_MODULES[args.entry]

    by_module = collections.Counter(
        e["args"].get("hlo_module", "?") for e in evs)
    picked = [(e["args"]["hlo_op"], e["dur"]) for e in evs
              if e["args"].get("hlo_module") == module]
    if not picked:
        raise SystemExit(
            f"{trace_file}: no device events for module {module!r} "
            f"(entry {args.entry}); modules present: "
            f"{dict(by_module.most_common(8))}")

    from homebrewnlp_tpu.analysis import cost_ledger
    ledger = cost_ledger.load_ledger(args.ledger)
    ledger_entry = (ledger or {}).get("entry_points", {}).get(args.entry)
    if ledger_entry is None:
        print(f"WARNING: no committed ledger entry for {args.entry!r}; "
              "flops/bytes columns will be empty", file=sys.stderr)

    rows, unattributed, total = attribute(picked, hlo, ledger_entry)

    other = sum(c for m, c in by_module.items() if m != module)
    print(f"== {args.entry} scope attribution "
          f"({total / 1e3 / args.steps:.2f} ms/step device time, "
          f"module {module}; {other} events of other modules ignored) ==")
    hdr = (f"{'scope':28s} {'ms/step':>9s} {'time%':>7s} {'flops%':>7s} "
           f"{'bytes%':>7s} {'bound':>8s}")
    print(hdr)

    def pct(v):
        return f"{v * 100:6.1f}%" if v is not None else "      -"

    for row in rows[:args.top]:
        flag = "  << overhead" if row["overhead"] else ""
        print(f"{row['scope']:28s} "
              f"{row['time_us'] / 1e3 / args.steps:9.2f} "
              f"{pct(row['time_share'])} {pct(row['flops_share'])} "
              f"{pct(row['bytes_share'])} "
              f"{(row['bound'] or '-'):>8s}{flag}")
    print(f"\nunattributed device time: {unattributed * 100:.1f}% "
          "(growing share = scope annotations or the HLO join broke)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
