#!/usr/bin/env python3
"""Routed top-k MoE training benchmark (configs/moe_mixer.json).

VERDICT r4 missing #2: routed MoE was implemented and dryrun-correct but
had no throughput number anywhere.  This is the standing measurement:
the flagship-class MoE recipe (d4096, depth 16 mixer halves, 8 experts,
top-2 routing, capacity 1.25, balance loss on) on one chip —
tokens/sec/chip + MFU (dual convention) like the other benches.  The MoE
model activates ~2/8 of its expert FF FLOPs per token; MFU counts the
FLOPs the jaxpr actually contains (dense dispatch/combine einsums + all
experts' matmuls — the capacity-bounded dense form computes every expert
over its buffer, so the denominator is the executed form, not an ideal
top-k), making the number comparable to the dense flagship's.

The EP story (experts sharded over 'model', dispatch/combine as
all-to-alls) is measured structurally by `scripts/pod_lowering.py
--config configs/moe_mixer.json` (collective inventory + per-chip memory
at the config's tpu_size-16 mesh) and functionally by the dryrun's routed
top-k MoE leg; this bench pins single-chip throughput.

Usage (real chip): python scripts/bench_moe.py [--steps 10]
Prints ONE JSON line like bench.py.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

WARMUP_STEPS = 2


def run(steps: int = 10) -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer

    cfg = json.load(open(os.path.join(os.path.dirname(os.path.abspath(
        __file__)), "..", "configs", "moe_mixer.json")))
    cfg.update(model_path="/tmp/bench_moe", use_checkpointing=False,
               tpu_size=1)
    cfg.pop("layout_override", None)
    if jax.default_backend() == "cpu":
        cfg.update(sequence_length=64, features_per_head=64, heads=2,
                   depth=2, train_batch_size=8, experts=4)
    params = ModelParameter(cfg)
    model = Model(params)
    trainer = Trainer(params, model)
    rng = np.random.default_rng(0)

    def make_batch():
        x = rng.integers(0, params.vocab_size,
                         (params.train_batch_size, params.sequence_length, 1))
        return {"token_x": jnp.asarray(x),
                "token_y": jnp.asarray((x + 1) % params.vocab_size)}

    t0 = time.monotonic()
    state = trainer.init_state(make_batch())
    print(f"setup {time.monotonic() - t0:.1f}s; compiling...", file=sys.stderr)
    t0 = time.monotonic()
    for _ in range(WARMUP_STEPS):
        state, metrics = trainer.step(state, make_batch())
    float(metrics["loss"])  # force the dispatched chain to completion
    print(f"compile+warmup {time.monotonic() - t0:.1f}s", file=sys.stderr)

    batches = [make_batch() for _ in range(steps)]
    t0 = time.monotonic()
    for batch in batches:
        state, metrics = trainer.step(state, batch)
    final_loss = float(metrics["loss"])
    dt = time.monotonic() - t0

    tokens = steps * params.train_batch_size * params.sequence_length
    n_chips = max(1, len(jax.devices()))
    out = {"metric": "LM tokens/sec/chip @ moe_mixer (8 experts, top-2)",
           "value": round(tokens / dt / n_chips, 2),
           "unit": "tokens/sec/chip",
           "final_loss": round(final_loss, 4)}
    try:
        from homebrewnlp_tpu.utils.flops import forward_flops_split, mfu
        fwd, fwd_exec = forward_flops_split(
            lambda v, b: trainer.model.apply(v, b).total_loss.data,
            state.variables, batches[0])
        out["mfu"] = round(mfu(fwd, dt / steps, n_chips), 4)
        causal = round(mfu(fwd_exec, dt / steps, n_chips), 4)
        if causal != out["mfu"]:
            out["mfu_causal"] = causal
    except Exception as exc:
        print(f"MFU computation failed: {exc}", file=sys.stderr)
    # routing health at the measured state: expert utilization + drop rate
    try:
        import numpy as np
        stats = trainer.moe_stats(state, batches[-1])
        util = [float(np.min(s["utilization"])) for s in stats.values()
                if "utilization" in s]
        drop = [float(np.mean(s["dropped_fraction"])) for s in stats.values()
                if "dropped_fraction" in s]
        if util:
            # true floor: the worst expert of the worst layer.  Labeled
            # *_at_init because this bench samples it after only ~10
            # synthetic steps — an essentially UNTRAINED router (measured
            # ~0.41 here vs 0.92 floors on the trained 1000-step run,
            # BASELINE.md round 5); the old unqualified name made the
            # artifact look like a routing-collapse bug (VERDICT weak #2)
            out["expert_utilization_min_at_init"] = round(min(util), 4)
        if drop:
            out["dropped_fraction_mean"] = round(sum(drop) / len(drop), 4)
    except Exception as exc:
        print(f"moe stats failed: {exc}", file=sys.stderr)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()
    print(json.dumps(run(args.steps)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
