#!/usr/bin/env python3
"""Lint: every ModelParameter config knob has a docs/CONFIG.md row.

Now a thin shim: the rule moved into the unified static-analysis layer as
``analysis/ast_lint.py``'s config-docs rule (run with every other rule by
``scripts/graft_lint.py --ast``; docs/STATIC_ANALYSIS.md).  This entry
point stays for muscle memory and for ``tests/config_docs_test.py``, and
keeps the original contract: exit 1 + a list on missing rows, no
third-party imports and no jax — the config module is parsed, never
executed (``ast_lint`` is stdlib-only and loaded by file path, so this
works without the package importable).
"""
from __future__ import annotations

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "_graft_ast_lint", os.path.join(REPO, "homebrewnlp_tpu", "analysis",
                                    "ast_lint.py"))
_ast_lint = importlib.util.module_from_spec(_spec)
# registered BEFORE exec: dataclasses resolves cls.__module__ there
sys.modules[_spec.name] = _ast_lint
_spec.loader.exec_module(_ast_lint)

CONFIG_PY = _ast_lint.CONFIG_PY
CONFIG_MD = _ast_lint.CONFIG_MD
INTERNAL = _ast_lint.INTERNAL
config_knobs = _ast_lint.config_knobs
documented_keys = _ast_lint.documented_keys
missing_knobs = _ast_lint.missing_knobs


def main() -> int:
    missing = missing_knobs()
    if missing:
        print(f"{len(missing)} config knob(s) have no docs/CONFIG.md row:")
        for k in missing:
            print(f"  {k}")
        print("add a `| `<knob>` | <default> | <meaning> |` row "
              "(docs/CONFIG.md)")
        return 1
    print("docs/CONFIG.md covers every config knob")
    return 0


if __name__ == "__main__":
    sys.exit(main())
