#!/usr/bin/env python3
"""Lint: every ModelParameter config knob has a docs/CONFIG.md row.

PRs 1-3 each hand-maintained this invariant when they added knobs; this
makes it mechanical.  The knob set is read from ``config.py`` by AST — the
``self.<name> = <default>`` assignments in ``ModelParameter.__init__``
BEFORE the ``for k, v in config.items()`` update loop (everything after it
is derived state, not configuration).  A knob counts as documented when it
appears as a `` `name` `` table-row key anywhere in docs/CONFIG.md.

Run standalone (exit 1 + a list on missing rows) or from the tier-1 test
``tests/config_docs_test.py``.  No third-party imports and no jax — the
config module is parsed, never executed.
"""
from __future__ import annotations

import ast
import os
import re
import sys
import typing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG_PY = os.path.join(REPO, "homebrewnlp_tpu", "config.py")
CONFIG_MD = os.path.join(REPO, "docs", "CONFIG.md")

#: internal bookkeeping assigned in the defaults section that is NOT a
#: config knob (everything else there is)
INTERNAL = {"unknown_config_keys"}


def config_knobs(source: str) -> typing.List[str]:
    """``self.X = default`` names from ModelParameter.__init__, up to the
    unknown-key update loop."""
    tree = ast.parse(source)
    init = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "ModelParameter":
            init = next(n for n in node.body
                        if isinstance(n, ast.FunctionDef)
                        and n.name == "__init__")
            break
    if init is None:
        raise AssertionError("ModelParameter.__init__ not found")
    knobs = []
    for stmt in init.body:
        if isinstance(stmt, ast.For):
            # the `for k, v in config.items()` loop ends the defaults
            # section; later assignments are validation/derivation
            break
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, ast.AnnAssign):
            targets = [stmt.target]
        else:
            continue
        for t in targets:
            if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == "self" and not t.attr.startswith("_")
                    and t.attr not in INTERNAL):
                knobs.append(t.attr)
    if len(knobs) < 50:  # the reference schema alone has ~150
        raise AssertionError(f"only {len(knobs)} knobs parsed — the "
                             "defaults-section detection broke")
    return knobs


def documented_keys(md: str) -> typing.Set[str]:
    """Keys of every ``| `name` | ...`` table row."""
    return set(re.findall(r"^\|\s*`([A-Za-z_][A-Za-z_0-9]*)`", md, re.M))


def missing_knobs(config_py: str = CONFIG_PY,
                  config_md: str = CONFIG_MD) -> typing.List[str]:
    with open(config_py) as f:
        knobs = config_knobs(f.read())
    with open(config_md) as f:
        documented = documented_keys(f.read())
    return sorted(set(k for k in knobs if k not in documented))


def main() -> int:
    missing = missing_knobs()
    if missing:
        print(f"{len(missing)} config knob(s) have no docs/CONFIG.md row:")
        for k in missing:
            print(f"  {k}")
        print("add a `| `<knob>` | <default> | <meaning> |` row "
              "(docs/CONFIG.md)")
        return 1
    print("docs/CONFIG.md covers every config knob")
    return 0


if __name__ == "__main__":
    sys.exit(main())
